"""Flight recorder (utils/flightrec): ring mechanics, cross-thread
context/tracer propagation through the work pool (the PR-4/5 gap), the
capture format (Chrome trace-event JSON), the slow-query log, the
queue_wait fetch phase, gc visibility, and the HTTP surface on both
vmsingle ('all') and vmselect ('select') role compositions.

The race-marked stress (concurrent writers + concurrent captures) runs
under VMT_RACETRACE=1 via tools/race.sh.
"""

from __future__ import annotations

import gc
import json
import threading
import time

import pytest

from victoriametrics_tpu.utils import flightrec
from victoriametrics_tpu.utils import metrics as metricslib
from victoriametrics_tpu.utils import querytracer

try:
    # the storage stack itself is the gate: ops/compress falls back to
    # zlib when the zstandard package is absent, so these run either way
    import victoriametrics_tpu.storage.storage  # noqa: F401
    _STORAGE_ERR = None
except ImportError as e:
    _STORAGE_ERR = e

needs_storage = pytest.mark.skipif(
    _STORAGE_ERR is not None,
    reason=f"storage deps unavailable: {_STORAGE_ERR}")

T0 = 1_753_700_000_000


@pytest.fixture(autouse=True)
def _recorder_enabled(monkeypatch):
    """Every test starts with the recorder ON and a clean thread ctx;
    tests that flip VM_FLIGHTREC call reconfigure() themselves and the
    teardown re-reads the restored env."""
    monkeypatch.delenv("VM_FLIGHTREC", raising=False)
    flightrec.reconfigure()
    flightrec.clear_ctx()
    yield
    flightrec.clear_ctx()
    monkeypatch.undo()
    flightrec.reconfigure()


class TestRing:
    def test_rec_and_capture_roundtrip(self):
        rec = flightrec.FlightRecorder(max_captures=4)
        t0 = time.perf_counter()
        time.sleep(0.002)
        flightrec.rec("t:roundtrip", t0, time.perf_counter() - t0,
                      arg="hello")
        cap = rec.capture("test", window_s=5.0)
        evs = [e for e in cap["trace"]["traceEvents"]
               if e["name"] == "t:roundtrip"]
        assert evs, "recorded span missing from capture"
        ev = evs[0]
        assert ev["ph"] == "X" and ev["dur"] >= 2_000  # µs
        assert ev["args"]["arg"] == "hello"
        assert cap["n_events"] >= 1 and cap["n_threads"] >= 1
        # the whole trace must be JSON-serializable (Perfetto-loadable)
        json.dumps(cap["trace"])

    def test_instant_event_format(self):
        rec = flightrec.FlightRecorder(max_captures=4)
        flightrec.instant("t:decision", arg="rebuild")
        cap = rec.capture("test", window_s=5.0)
        evs = [e for e in cap["trace"]["traceEvents"]
               if e["name"] == "t:decision"]
        assert evs and evs[0]["ph"] == "i" and "dur" not in evs[0]
        assert evs[0]["s"] == "t"

    def test_ring_wraparound_keeps_newest(self, monkeypatch):
        """A lapped ring keeps the LAST cap events; the overwritten ones
        are counted into vm_flight_dropped_events_total at capture."""
        monkeypatch.setenv("VM_FLIGHTREC_EVENTS", "8")
        out = {}

        def run():
            base = time.perf_counter()
            for k in range(20):
                flightrec.rec(f"wrap:{k}", base + k * 1e-7, 1e-8)
            out["ring"] = flightrec._tls.ring

        t = threading.Thread(target=run)
        t.start()
        t.join(10)
        ring = out["ring"]
        assert ring.cap == 8 and ring.i == 20
        names = [e[2] for e in ring.snapshot(0.0)]
        # cap=8 retains cursors 12..19; the seqlock filter drops the
        # oldest retained cursor too (it is the one slot a mid-store
        # writer could be tearing — conservative, never misattributing)
        assert names == [f"wrap:{k}" for k in range(13, 20)]
        dropped = metricslib.REGISTRY.counter(
            "vm_flight_dropped_events_total")
        d0 = dropped.get()
        flightrec.FlightRecorder(max_captures=2).capture(
            "test", window_s=60.0)
        # 20 written, 8 retained, none previously captured -> >= 12
        # (other threads' rings may contribute more, never less)
        assert dropped.get() - d0 >= 12

    def test_taken_is_first_uncaptured_cursor(self, monkeypatch):
        """After a capture, ring.taken points at the first cursor NOT
        yet captured — so a later wrap past already-captured events
        reports zero drops (the off-by-one counted the last captured
        event as lost once per wrap: false drops on a lossless ring)."""
        monkeypatch.setenv("VM_FLIGHTREC_EVENTS", "8")
        out = {}

        def run():
            base = time.perf_counter()
            for k in range(6):
                flightrec.rec(f"taken:{k}", base + k * 1e-7, 1e-8)
            out["ring"] = flightrec._tls.ring

        t = threading.Thread(target=run)
        t.start()
        t.join(10)
        flightrec.FlightRecorder(max_captures=2).capture(
            "test", window_s=60.0)
        ring = out["ring"]
        assert ring.i == 6
        assert ring.taken == 6, \
            "taken must be first-uncaptured (last captured cursor + 1)"

    def test_capture_merge_is_timestamp_ordered(self):
        """Events from different thread rings interleave in ts order in
        the merged trace (Perfetto requires no ordering, but the summary
        and human eyes do)."""
        now = time.perf_counter()
        offs = {"ordtest:a0": 1e-4, "ordtest:a1": 3e-4,
                "ordtest:b0": 0.0, "ordtest:b1": 2e-4}

        def writer(names):
            for n in names:
                flightrec.rec(n, now - 0.01 + offs[n], 1e-4)

        ta = threading.Thread(target=writer,
                              args=(["ordtest:a0", "ordtest:a1"],))
        tb = threading.Thread(target=writer,
                              args=(["ordtest:b0", "ordtest:b1"],))
        for t in (ta, tb):
            t.start()
        for t in (ta, tb):
            t.join(10)
        cap = flightrec.FlightRecorder(max_captures=2).capture(
            "test", window_s=5.0)
        ours = [e for e in cap["trace"]["traceEvents"]
                if e["name"].startswith("ordtest:")]
        assert [e["name"] for e in ours] == \
            ["ordtest:b0", "ordtest:a0", "ordtest:b1", "ordtest:a1"]
        ts = [e["ts"] for e in ours]
        assert ts == sorted(ts)

    def test_disabled_is_a_noop(self, monkeypatch):
        monkeypatch.setenv("VM_FLIGHTREC", "0")
        flightrec.reconfigure()
        assert not flightrec.enabled()
        n_rings = len(flightrec._rings)

        def run():
            # rec() must return before touching TLS: no ring is created
            flightrec.rec("off:span", time.perf_counter(), 1e-3)
            flightrec.instant("off:instant")

        t = threading.Thread(target=run)
        t.start()
        t.join(10)
        assert len(flightrec._rings) == n_rings
        assert flightrec.FlightRecorder(max_captures=2).capture(
            "test") is None

    def test_dead_thread_rings_are_reclaimed(self):
        """A dead thread's ring stays capturable while its events are
        inside the retention window, then is pruned — per-connection
        handler threads must not leak one ring each forever."""
        old_t0 = time.perf_counter() - 7200.0
        fresh_t0 = time.perf_counter()
        rings = {}

        def run(key, t0):
            flightrec.rec(f"reclaim:{key}", t0, 1e-3)
            rings[key] = flightrec._tls.ring

        # "old" created LAST: nothing prunes it between creation and
        # the capture below (ring creation prunes stale dead rings too)
        for key, t0 in (("fresh", fresh_t0), ("old", old_t0)):
            t = threading.Thread(target=run, args=(key, t0))
            t.start()
            t.join(10)
        with flightrec._rings_lock:
            assert rings["old"] in flightrec._rings
        # a capture prunes dead rings past the retention window: the
        # stale ring goes, the recent one survives
        flightrec.FlightRecorder(max_captures=2).capture(
            "test", window_s=5.0)
        with flightrec._rings_lock:
            assert rings["old"] not in flightrec._rings
            assert rings["fresh"] in flightrec._rings

    def test_capture_ring_is_bounded(self):
        rec = flightrec.FlightRecorder(max_captures=2)
        flightrec.instant("t:x")
        ids = [rec.capture("test", window_s=5.0)["id"] for _ in range(3)]
        listed = [c["id"] for c in rec.list()]
        assert listed == [ids[2], ids[1]]      # newest first, oldest gone
        assert rec.get(ids[0]) is None
        assert rec.get(ids[2])["id"] == ids[2]
        # list() metadata excludes the trace body
        assert all("trace" not in c for c in rec.list())


class TestSummary:
    def test_overlap_attribution_excludes_own_work(self):
        """The slow-refresh summary charges OTHER-context work
        overlapping the serve window, bucketed by category prefix —
        including ambient work on the serve thread itself (a gc pause
        stalling the refresh is interference, not the query's work)."""
        evs = [
            {"name": "serve:refresh", "ph": "X", "pid": 1, "tid": 1,
             "ts": 0.0, "dur": 100_000.0, "args": {"ctx": 7}},
            # other thread, no ctx: full 50ms inside the window
            {"name": "merge:part", "ph": "X", "pid": 1, "tid": 2,
             "ts": 10_000.0, "dur": 50_000.0},
            # SAME thread as the serve, ctx 0: a gc pause on the serving
            # thread counts — the tid is not an exclusion criterion
            {"name": "gc:gen0", "ph": "X", "pid": 1, "tid": 1,
             "ts": 40_000.0, "dur": 10_000.0},
            # the query's OWN fetch work (same ctx): excluded
            {"name": "fetch:rollup", "ph": "X", "pid": 1, "tid": 3,
             "ts": 0.0, "dur": 30_000.0, "args": {"ctx": 7}},
            # partial overlap: only the first 5ms counts
            {"name": "gc:gen2", "ph": "X", "pid": 1, "tid": 4,
             "ts": 95_000.0, "dur": 20_000.0},
            # instant events never contribute duration
            {"name": "rcache:inplace", "ph": "i", "pid": 1, "tid": 1,
             "ts": 5.0, "s": "t"},
            # pure waits are deference, not interference: a merge
            # sleeping in the serve-priority yield must NOT be charged
            # as merge overlap — it goes to the waiting bucket
            {"name": "merge:yield", "ph": "X", "pid": 1, "tid": 5,
             "ts": 0.0, "dur": 80_000.0},
            {"name": "fetch:queue_wait", "ph": "X", "pid": 1, "tid": 6,
             "ts": 20_000.0, "dur": 30_000.0},
            # nested fan spans (flush:table contains its workers'
            # flush:part): per-category interval UNION, not a sum —
            # coverage can never exceed the refresh window
            {"name": "flush:table", "ph": "X", "pid": 1, "tid": 7,
             "ts": 10_000.0, "dur": 60_000.0},
            {"name": "flush:part", "ph": "X", "pid": 1, "tid": 8,
             "ts": 15_000.0, "dur": 50_000.0},
        ]
        s = flightrec.summarize(evs)
        assert s["slow_refresh"]["ms"] == 100.0
        assert s["slow_refresh"]["ctx"] == 7
        assert s["slow_refresh"]["overlap_ms_by_category"] == \
            {"merge": 50.0, "gc": 15.0, "flush": 60.0}
        assert s["slow_refresh"]["waiting_ms_by_name"] == \
            {"merge:yield": 80.0, "fetch:queue_wait": 30.0}
        assert s["span_ms_by_name"]["merge:part"] == 50.0

    def test_focus_ctx_pins_the_triggering_refresh(self):
        """A slow-refresh capture explains the refresh that TRIPPED it,
        even when a bigger serve span (the cold first eval) shares the
        window; unknown ctx falls back to the slowest serve."""
        evs = [
            # the cold first eval: huge, ctx 1, nothing overlaps it
            {"name": "serve:refresh", "ph": "X", "pid": 1, "tid": 1,
             "ts": 0.0, "dur": 900_000.0, "args": {"ctx": 1}},
            # the triggering steady refresh: ctx 5, later, smaller
            {"name": "serve:refresh", "ph": "X", "pid": 1, "tid": 1,
             "ts": 1_000_000.0, "dur": 200_000.0, "args": {"ctx": 5}},
            {"name": "flush:part", "ph": "X", "pid": 1, "tid": 2,
             "ts": 1_050_000.0, "dur": 100_000.0},
        ]
        s = flightrec.summarize(evs, focus_ctx=5)
        assert s["slow_refresh"]["ctx"] == 5
        assert s["slow_refresh"]["ms"] == 200.0
        assert s["slow_refresh"]["overlap_ms_by_category"] == \
            {"flush": 100.0}
        # no focus (on-demand): slowest serve wins
        assert flightrec.summarize(evs)["slow_refresh"]["ctx"] == 1
        # stale focus (refresh span already aged out): fall back too
        assert flightrec.summarize(
            evs, focus_ctx=99)["slow_refresh"]["ctx"] == 1


class TestCrossThreadPropagation:
    def test_pool_worker_inherits_ctx_and_tracer(self, monkeypatch):
        """A task submitted to the shared pool runs under the SUBMITTING
        query's flight context and tracer: its spans land in ctx_events
        and its trace children attach to the query's tree (the PR-4/5
        propagation gap this PR closes)."""
        from victoriametrics_tpu.utils import workpool
        monkeypatch.setenv("VM_SEARCH_WORKERS", "2")
        ctx = flightrec.new_ctx()
        prev_ctx = flightrec.set_ctx(ctx)
        tracer = querytracer.Tracer("query root")
        prev_tr = querytracer.set_current(tracer)
        started = threading.Event()
        release = threading.Event()
        info = {}
        main_tid = threading.get_ident()

        def task():
            started.set()
            release.wait(10)
            info["tid"] = threading.get_ident()
            info["ctx"] = flightrec.get_ctx()
            with querytracer.current().new_child("worker side") as c:
                c.donef("ok")
            with flightrec.span("t:worker"):
                time.sleep(0.001)
            return 42

        try:
            fut = workpool.POOL.submit(task)
            # the main thread has NOT entered result() yet, so the task
            # is necessarily running on a pool worker thread
            assert started.wait(10), "pool never started the task"
            release.set()
            assert fut.result() == 42
        finally:
            querytracer.set_current(prev_tr)
            flightrec.set_ctx(prev_ctx)
        assert info["tid"] != main_tid
        assert info["ctx"] == ctx
        # the worker's span is reassembled under the query's ctx ...
        evs = flightrec.ctx_events(ctx)
        by_name = {name for _t0, _dur, name, _tid in evs}
        assert "t:worker" in by_name
        assert "pool:task" in by_name           # the pool's own task span
        assert "pool:queue_wait" in by_name     # and its queue wait
        worker_tids = {tid for _t0, _dur, name, tid in evs
                       if name == "t:worker"}
        assert worker_tids == {info["tid"]}
        # ... the phase split sums it ...
        split = flightrec.phase_split(ctx)
        assert split.get("t:worker", 0.0) > 0.0
        # ... and the tracer child attached to the submitting tree
        d = tracer.to_dict()
        msgs = [c["message"] for c in d.get("children", ())]
        assert "worker side: ok" in msgs

    def test_ctx_restored_after_task(self, monkeypatch):
        """Workers must not leak a finished task's ctx into the next."""
        from victoriametrics_tpu.utils import workpool
        monkeypatch.setenv("VM_SEARCH_WORKERS", "2")
        ctx = flightrec.new_ctx()
        prev = flightrec.set_ctx(ctx)
        try:
            workpool.POOL.run([lambda: None] * 4)
        finally:
            flightrec.set_ctx(prev)
        seen = []
        done = threading.Event()

        def probe():
            seen.append(flightrec.get_ctx())
            done.set()

        flightrec.clear_ctx()
        workpool.POOL.submit(probe).result()
        assert done.wait(10)
        assert seen == [0]


class TestQueueWaitPhase:
    def test_search_gate_wait_ticks_queue_wait_phase(self):
        """Time spent queued at the SearchGate lands in
        vm_fetch_phase_seconds_total{phase="queue_wait"} (the previously
        invisible slice: without it the phase split doesn't sum to
        contended wall time)."""
        from victoriametrics_tpu.utils.workpool import SearchGate
        qw = metricslib.REGISTRY.float_counter(
            'vm_fetch_phase_seconds_total{phase="queue_wait"}')
        v0 = qw.get()
        gate = SearchGate(limit=1, max_queue_ms=5000)
        release = threading.Event()
        entered = threading.Event()

        def hold():
            with gate:
                entered.set()
                release.wait(10)

        t = threading.Thread(target=hold, daemon=True)
        t.start()
        assert entered.wait(10)
        t2_done = threading.Event()

        def queued():
            with gate:
                t2_done.set()

        t2 = threading.Thread(target=queued, daemon=True)
        t2.start()
        time.sleep(0.05)        # let the second caller actually queue
        release.set()
        assert t2_done.wait(10)
        t.join(10)
        t2.join(10)
        assert qw.get() - v0 >= 0.03
        # and the wait is visible on the flight timeline
        cap = flightrec.FlightRecorder(max_captures=2).capture(
            "test", window_s=5.0)
        assert any(e["name"] == "fetch:queue_wait"
                   for e in cap["trace"]["traceEvents"])


class TestGcVisibility:
    def test_gc_pause_metrics_and_flight_event(self):
        pause = metricslib.REGISTRY.float_counter(
            "vm_gc_pause_seconds_total")
        p0 = pause.get()
        gc.collect()
        assert pause.get() > p0
        # per-generation collection counts in the exposition
        text = metricslib.REGISTRY.write_prometheus()
        assert 'vm_gc_collections_total{gen="0"}' in text
        assert 'vm_gc_collections_total{gen="2"}' in text
        assert "# TYPE vm_gc_pause_seconds_total counter" in text
        # and the pause is a span on the flight timeline
        cap = flightrec.FlightRecorder(max_captures=2).capture(
            "test", window_s=5.0)
        assert any(e["name"].startswith("gc:gen")
                   for e in cap["trace"]["traceEvents"])


class TestSlowQueryLog:
    def test_threshold_and_ring(self):
        from victoriametrics_tpu.query.querystats import SlowQueryLog
        log = SlowQueryLog(max_records=2, threshold_ms=10.0)
        total = metricslib.REGISTRY.counter("vm_slow_queries_total")
        t0 = total.get()
        assert not log.maybe_record("fast", 0, 1, 15, (0, 0), 0.001)
        assert log.snapshot() == []
        assert log.maybe_record("slow1", 0, 1, 15, (0, 0), 0.5)
        assert log.maybe_record("slow2", 0, 1, 15, (0, 0), 0.6,
                                capture_id=7)
        assert log.maybe_record("slow3", 0, 1, 15, None, 0.7)
        assert total.get() - t0 == 3
        snap = log.snapshot()                    # newest first, bounded
        assert [r["query"] for r in snap] == ["slow3", "slow2"]
        assert snap[1]["flightCaptureId"] == 7
        assert "flightCaptureId" not in snap[0]
        assert snap[0]["tenant"] == "0:0"

    def test_phase_split_from_flight_ctx(self):
        from victoriametrics_tpu.query.querystats import SlowQueryLog
        log = SlowQueryLog(max_records=4, threshold_ms=1.0)
        ctx = flightrec.new_ctx()
        prev = flightrec.set_ctx(ctx)
        try:
            t0 = time.perf_counter()
            time.sleep(0.002)
            flightrec.rec("fetch:index_search", t0,
                          time.perf_counter() - t0)
        finally:
            flightrec.set_ctx(prev)
        assert log.maybe_record("q", 0, 1, 15, (0, 0), 0.05, ctx=ctx)
        rec0 = log.snapshot()[0]
        assert rec0["phaseSplitMs"].get("fetch:index_search", 0.0) >= 1.0


@pytest.mark.race
class TestRaceStress:
    def test_concurrent_writers_and_captures(self):
        """Writers hammer their rings while captures walk them: the
        seqlock-reader discipline must never produce a torn event or an
        unserializable trace (race-marked; tools/race.sh runs this under
        VMT_RACETRACE=1)."""
        errs = []
        stop = threading.Event()

        def writer(k):
            try:
                n = 0
                while not stop.is_set() and n < 20_000:
                    with flightrec.span(f"race:w{k}", arg=n):
                        n += 1
                    flightrec.instant(f"race:i{k}")
            except Exception as e:  # noqa: BLE001 — reported below
                errs.append(e)

        threads = [threading.Thread(target=writer, args=(k,), daemon=True)
                   for k in range(4)]
        rec = flightrec.FlightRecorder(max_captures=4)
        for t in threads:
            t.start()
        try:
            for _ in range(10):
                cap = rec.capture("race", window_s=5.0)
                json.dumps(cap["trace"])        # serializable every time
                for ev in cap["trace"]["traceEvents"]:
                    assert ev["ph"] in ("X", "i", "M")
        finally:
            stop.set()
            for t in threads:
                t.join(10)
        assert not errs


# -- HTTP surface -------------------------------------------------------------


@pytest.fixture()
def app(tmp_path):
    """In-process vmsingle (same shape as test_vmsingle_http.app)."""
    from tests.apptest_helpers import Client
    from victoriametrics_tpu.apps.vmsingle import build, parse_flags
    args = parse_flags([f"-storageDataPath={tmp_path}/data",
                        "-httpListenAddr=127.0.0.1:0"])
    storage, srv, api = build(args)
    srv.start()
    yield Client(srv.port)
    srv.stop()
    storage.close()


def _ingest(app, name="fm", n=3):
    lines = "".join(f'{name}{{i="{k}"}} {k} {T0 + j * 15_000}\n'
                    for k in range(n) for j in range(20))
    code, _ = app.post("/api/v1/import/prometheus", lines.encode())
    assert code == 204


@needs_storage
class TestHTTPFlight:
    def test_capture_list_fetch_and_errors(self, app):
        code, body = app.get("/api/v1/status/flight", capture="1")
        assert code == 200
        data = json.loads(body)
        cap_id = data["captured"]
        assert any(c["id"] == cap_id for c in data["data"])
        # fetch-by-id returns the bare Chrome trace-event object
        code, body = app.get("/api/v1/status/flight", id=str(cap_id))
        assert code == 200
        trace = json.loads(body)
        assert isinstance(trace["traceEvents"], list) and trace["traceEvents"]
        for ev in trace["traceEvents"]:
            assert {"name", "ph", "pid", "tid"} <= set(ev)
            if ev["ph"] == "X":
                assert "ts" in ev and "dur" in ev
        # the list never inlines trace bodies
        code, body = app.get("/api/v1/status/flight")
        assert code == 200
        lst = json.loads(body)["data"]
        assert lst and all("trace" not in c for c in lst)
        assert all("summary" in c for c in lst)
        code, _ = app.get("/api/v1/status/flight", id="bogus")
        assert code == 422
        code, _ = app.get("/api/v1/status/flight", id="99999999")
        assert code == 404

    def test_disabled_returns_503(self, app, monkeypatch):
        monkeypatch.setenv("VM_FLIGHTREC", "0")
        flightrec.reconfigure()
        try:
            code, _ = app.get("/api/v1/status/flight")
            assert code == 503
        finally:
            monkeypatch.delenv("VM_FLIGHTREC")
            flightrec.reconfigure()

    def test_slow_query_log_links_flight_capture(self, app, monkeypatch):
        """A served query over the slow thresholds produces (1) a
        slow-query record with a cross-thread per-phase split and (2) a
        linked flight capture whose timeline contains the serve span."""
        _ingest(app)
        monkeypatch.setenv("VM_SLOW_QUERY_MS", "0.000001")
        monkeypatch.setenv("VM_SLOW_REFRESH_MS", "0.000001")
        res = app.query_range("fm", T0 / 1e3, (T0 + 300_000) / 1e3, 15)
        assert res["status"] == "success"
        code, body = app.get("/api/v1/status/slow_queries")
        assert code == 200
        data = json.loads(body)
        assert data["status"] == "ok"
        recs = [r for r in data["data"] if r["query"] == "fm"]
        assert recs, "slow query not recorded"
        rec0 = recs[0]
        assert rec0["durationSeconds"] > 0
        assert rec0["phaseSplitMs"], "no per-phase split reassembled"
        # containers (the whole refresh, pool task wrappers) are split
        # out so phaseSplitMs holds disjoint phases, not double counts
        assert "serve:refresh" in rec0.get("containerSpansMs", {})
        assert not any(k in ("serve:refresh", "pool:task")
                       for k in rec0["phaseSplitMs"])
        cap_id = rec0.get("flightCaptureId")
        assert cap_id is not None, "slow refresh tripped no capture"
        code, body = app.get("/api/v1/status/flight", id=str(cap_id))
        assert code == 200
        names = {e["name"] for e in json.loads(body)["traceEvents"]}
        assert "serve:refresh" in names

    def test_fast_queries_stay_out_of_the_log(self, app, monkeypatch):
        _ingest(app, name="fastm")
        monkeypatch.setenv("VM_SLOW_QUERY_MS", "1e9")
        app.query_range("fastm", T0 / 1e3, (T0 + 300_000) / 1e3, 15)
        code, body = app.get("/api/v1/status/slow_queries")
        data = json.loads(body)
        assert not [r for r in data["data"] if r["query"] == "fastm"]
        assert data["thresholdMs"] == 1e9


@needs_storage
class TestSelectModeHTTP:
    def test_select_role_serves_flight_and_slowlog(self, tmp_path):
        """The vmselect role composition (register(mode='select'))
        carries both status endpoints too — they live in
        _register_select, exactly like the reference's vmselect-only
        status handlers."""
        from tests.apptest_helpers import Client
        from victoriametrics_tpu.httpapi.prometheus_api import PrometheusAPI
        from victoriametrics_tpu.httpapi.server import HTTPServer
        from victoriametrics_tpu.storage.storage import Storage
        s = Storage(str(tmp_path / "data"))
        srv = HTTPServer("127.0.0.1", 0)
        PrometheusAPI(s).register(srv, mode="select")
        srv.start()
        try:
            c = Client(srv.port)
            code, body = c.get("/api/v1/status/flight", capture="1")
            assert code == 200
            cap_id = json.loads(body)["captured"]
            code, body = c.get("/api/v1/status/flight", id=str(cap_id))
            assert code == 200 and "traceEvents" in json.loads(body)
            code, body = c.get("/api/v1/status/slow_queries")
            assert code == 200
            data = json.loads(body)
            assert data["status"] == "ok" and "thresholdMs" in data
        finally:
            srv.stop()
            s.close()
