"""Test config: force JAX onto a virtual 8-device CPU mesh so multi-chip
sharding paths are exercised without TPU hardware.

The axon TPU plugin in this image overrides JAX_PLATFORMS at import time, so
the env var alone is not enough — we also update jax.config after import.
Set VMTPU_TEST_TPU=1 to run the suite against the real chip instead.
"""

import os

os.environ.setdefault("JAX_ENABLE_X64", "1")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if not os.environ.get("VMTPU_TEST_TPU"):
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "race: concurrency/race-detector tests "
        "(tools/race.sh runs these under VMT_RACETRACE=1)")
    config.addinivalue_line("markers", "slow: excluded from tier-1 (-m 'not slow')")
