"""Test config: force JAX onto a virtual 8-device CPU mesh so multi-chip
sharding paths are exercised without TPU hardware.

The axon TPU plugin in this image overrides JAX_PLATFORMS at import time, so
the env var alone is not enough — we also update jax.config after import.
Set VMTPU_TEST_TPU=1 to run the suite against the real chip instead.
"""

import os

os.environ.setdefault("JAX_ENABLE_X64", "1")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402

if not os.environ.get("VMTPU_TEST_TPU"):
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "race: concurrency/race-detector tests "
        "(tools/race.sh runs these under VMT_RACETRACE=1)")
    config.addinivalue_line("markers", "slow: excluded from tier-1 (-m 'not slow')")
    config.addinivalue_line(
        "markers", "crash: kill -9 crash-recovery matrix "
        "(tools/chaos.sh runs these; the full matrix is also slow-marked)")
    config.addinivalue_line(
        "markers", "requires_native: needs the native codec library "
        "(libvmcodec.so); skipped cleanly on minimal containers without "
        "a C++ toolchain instead of erroring")


def pytest_collection_modifyitems(config, items):
    try:
        from victoriametrics_tpu import native
        have_native = native.available()
    except Exception:
        have_native = False
    if have_native:
        return
    skip = pytest.mark.skip(
        reason="native codec library unavailable (no g++ / libvmcodec.so)")
    for item in items:
        if "requires_native" in item.keywords:
            item.add_marker(skip)
