"""Per-tenant QoS admission (utils/workpool.TenantGate), deadline-
propagating RPC, and the shed-load / partial-result HTTP surfaces.

The fast half of the robustness suite (tier-1): quota parsing, admission
semantics, priority classes, the race-marked TenantGate stress under the
deterministic scheduler, RPC deadline/backoff behavior against real
in-process RPC servers, and the killed-node regression (partial=True
with the surviving node's exact rows).  The process-level chaos
scenarios live in tests/test_chaos_cluster.py (slow-marked).
"""

import json
import threading
import time
import warnings

import numpy as np
import pytest

from victoriametrics_tpu.devtools import faultinject, racetrace
from victoriametrics_tpu.devtools.sched import DeterministicScheduler
from victoriametrics_tpu.parallel.cluster_api import (ClusterStorage,
                                                      make_storage_handlers)
from victoriametrics_tpu.parallel.rpc import (HELLO_INSERT, HELLO_SELECT,
                                              RPCClient, RPCDeadlineError,
                                              RPCError, RPCServer, Writer)
from victoriametrics_tpu.utils import workpool
from victoriametrics_tpu.utils.workpool import (SearchLimitError,
                                                TenantGate, TenantQuota,
                                                parse_tenant_quotas)

T0 = 1_753_700_000_000


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    yield
    faultinject.configure("")


# ---------------------------------------------------------------------------
# quota parsing
# ---------------------------------------------------------------------------

class TestQuotaParsing:
    def test_full_grammar(self):
        q = parse_tenant_quotas("0:0=8:5000:high;7=2:100:low;*=4")
        assert q[(0, 0)].limit == 8
        assert q[(0, 0)].queue_ms == 5000.0
        assert q[(0, 0)].priority == "high"
        assert q[(7, 0)].limit == 2 and q[(7, 0)].rank == 2
        assert q["*"].limit == 4
        assert q["*"].queue_ms is None  # inherits the gate default
        assert q["*"].priority == "normal"

    def test_malformed_entries_dropped_not_fatal(self):
        q = parse_tenant_quotas("1:0=2;garbage;x=y;3:0=nope;4:0=1:bad")
        assert list(q) == [(1, 0)]

    def test_negative_limit_dropped(self):
        # a negative cap would make the tenant permanently inadmissible
        assert parse_tenant_quotas("7=-1") == {}

    def test_empty_means_no_quotas(self):
        assert parse_tenant_quotas("") == {}

    def test_gate_rereads_env(self, monkeypatch):
        g = TenantGate(limit=4)
        monkeypatch.setenv("VM_TENANT_QUOTAS", "5:0=3")
        assert g.quota_for((5, 0)).limit == 3
        monkeypatch.setenv("VM_TENANT_QUOTAS", "5:0=1")
        assert g.quota_for((5, 0)).limit == 1
        monkeypatch.delenv("VM_TENANT_QUOTAS")
        assert g.quota_for((5, 0)).limit == 0  # back to global-only


# ---------------------------------------------------------------------------
# admission semantics
# ---------------------------------------------------------------------------

class TestTenantGate:
    def test_default_behaves_like_global_gate(self):
        g = TenantGate(limit=2, max_queue_ms=50, quotas={})
        with g.admit((1, 0)), g.admit((2, 0)):
            assert g.occupancy()[0] == 2
            t0 = time.perf_counter()
            with pytest.raises(SearchLimitError):
                with g.admit((3, 0)):
                    pass
            assert time.perf_counter() - t0 < 2.0
        assert g.occupancy() == (0, {})

    def test_tenant_quota_isolates(self):
        g = TenantGate(limit=4, max_queue_ms=5000,
                       quotas={(1, 0): TenantQuota(1, queue_ms=60)})
        with g.admit((1, 0)):
            # tenant 1 is at ITS cap: rejected within its queue budget
            t0 = time.perf_counter()
            with pytest.raises(SearchLimitError) as ei:
                with g.admit((1, 0)):
                    pass
            assert time.perf_counter() - t0 < 2.0
            assert "tenant quota" in str(ei.value)
            # other tenants sail through the remaining global capacity
            with g.admit((2, 0)), g.admit((2, 0)), g.admit((2, 0)):
                assert g.occupancy()[0] == 4

    def test_release_grants_queued_waiter(self):
        g = TenantGate(limit=1, max_queue_ms=5000, quotas={})
        got = []

        with g.admit((1, 0)):
            t = threading.Thread(
                target=lambda: got.append(g.admit((2, 0)).__enter__()))
            t.start()
            time.sleep(0.1)
            assert not got  # queued behind the held slot
        t.join(timeout=5)
        assert got  # released slot was handed over
        g._release((2, 0))
        assert g.occupancy() == (0, {})

    def test_priority_classes_order_grants(self):
        """When capacity frees up, a queued high-priority request is
        granted before an earlier-arrived low-priority one."""
        g = TenantGate(limit=1, max_queue_ms=5000,
                       quotas={(1, 0): TenantQuota(0, priority="low"),
                               (2, 0): TenantQuota(0, priority="high")})
        order = []
        threads = []

        def worker(tenant, tag):
            with g.admit(tenant):
                order.append(tag)
                time.sleep(0.05)

        with g.admit((9, 9)):  # hold the only slot
            for tenant, tag in (((1, 0), "low"), ((2, 0), "high")):
                t = threading.Thread(target=worker, args=(tenant, tag))
                t.start()
                threads.append(t)
                time.sleep(0.1)  # deterministic arrival order: low first
        for t in threads:
            t.join(timeout=5)
        assert order == ["high", "low"]

    def test_quota_capped_waiter_does_not_block_other_tenants(self):
        """A waiter blocked only by its OWN tenant quota must not
        head-of-line block later waiters of other tenants."""
        g = TenantGate(limit=2, max_queue_ms=3000,
                       quotas={(1, 0): TenantQuota(1)})
        passed = []
        with g.admit((1, 0)):  # tenant 1 at quota, one global slot free
            blocked = threading.Thread(
                target=lambda: passed.append(("t1", g.admit(
                    (1, 0)).__enter__())))
            blocked.daemon = True
            blocked.start()
            time.sleep(0.1)
            # tenant 2 must be admitted despite tenant 1 queued ahead
            t0 = time.perf_counter()
            with g.admit((2, 0)):
                assert time.perf_counter() - t0 < 1.0
        blocked.join(timeout=5)  # tenant 1's waiter gets the freed slot
        assert passed, "queued tenant-1 waiter never admitted"
        g._release((1, 0))
        assert g.occupancy() == (0, {})

    def test_tenant_metric_cardinality_bounded(self):
        """Tenant ids come from the URL path: iterating ids must fold
        past the cap into one shared 'other' label set without growing
        the memo per tenant."""
        g = TenantGate(limit=4, quotas={})
        g._MAX_TENANT_METRICS = 3
        for i in range(10):
            with g.admit((i, 0)):
                pass
        # 3 real tenants x 2 metric names + 1 shared "other" per name
        assert len(g._tenant_label_seen) == 3
        names = {k for k in g._tenant_metric_memo}
        other_keys = [k for k in names if k[1] == "other"]
        per_tenant_keys = [k for k in names if k[1] != "other"]
        assert {t for _, t in per_tenant_keys} == {(0, 0), (1, 0), (2, 0)}
        assert other_keys  # folded tenants share these
        # folding is sticky per tenant: repeat admits add no new keys
        before = len(g._tenant_metric_memo)
        with g.admit((9, 0)):
            pass
        assert len(g._tenant_metric_memo) == before

    def test_concurrent_metrics_and_rejection_counters(self):
        from victoriametrics_tpu.utils import metrics as metricslib
        g = TenantGate(limit=1, max_queue_ms=30,
                       quotas={(8, 1): TenantQuota(1, queue_ms=30)})
        with g.admit((8, 1)):
            with pytest.raises(SearchLimitError):
                with g.admit((8, 1)):
                    pass
        text = metricslib.REGISTRY.write_prometheus()
        assert 'vm_tenant_search_requests_total{tenant="8:1"}' in text
        assert 'vm_tenant_search_rejected_total{tenant="8:1"}' in text


# ---------------------------------------------------------------------------
# race-marked stress: quota never exceeded, starvation-free
# ---------------------------------------------------------------------------

@pytest.fixture
def race_on():
    was = racetrace.enabled()
    racetrace.enable()
    racetrace.reset()
    yield
    if not was:
        racetrace.disable()


@pytest.mark.race
class TestTenantGateRace:
    def _stress(self, seed):
        racetrace.reset()
        sched = DeterministicScheduler(seed=seed, change_prob=0.2,
                                       step_timeout=2.0)
        gate = TenantGate(limit=2, max_queue_ms=60_000,
                          quotas={(1, 0): TenantQuota(1),
                                  (2, 0): TenantQuota(1)})
        peak = {"global": 0, (1, 0): 0, (2, 0): 0}
        done = []
        lk = threading.Lock()

        def worker(tenant, tag):
            for _ in range(3):
                with gate.admit(tenant):
                    g, per = gate.occupancy()
                    with lk:
                        peak["global"] = max(peak["global"], g)
                        peak[tenant] = max(peak[tenant],
                                           per.get(tenant, 0))
            done.append(tag)

        for i in range(2):
            sched.spawn(f"a{i}", worker, (1, 0), f"a{i}")
            sched.spawn(f"b{i}", worker, (2, 0), f"b{i}")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            sched.run(timeout=60)
        return peak, sorted(done), racetrace.reports()

    def test_quota_never_exceeded_and_starvation_free(self, race_on):
        """Under seeded adversarial interleavings: the per-tenant caps
        and the global cap hold at every observation point, every
        worker of both tenants completes (starvation-freedom), and the
        sanitizer sees no races on the gate's shared state."""
        peak, done, reports = self._stress(31337)
        assert peak["global"] <= 2
        assert peak[(1, 0)] <= 1
        assert peak[(2, 0)] <= 1
        assert done == ["a0", "a1", "b0", "b1"]
        gate_races = [r for r in reports if "TenantGate" in str(r.field)]
        assert not gate_races, gate_races

    def test_same_seed_same_outcome(self, race_on):
        assert self._stress(99)[:2] == self._stress(99)[:2]


# ---------------------------------------------------------------------------
# RPC deadline propagation + killed-node regression (in-process cluster)
# ---------------------------------------------------------------------------

class _Node:
    """In-process vmstorage: Storage + real TCP RPC servers."""

    def __init__(self, path):
        from victoriametrics_tpu.storage.storage import Storage
        self.storage = Storage(str(path))
        handlers = make_storage_handlers(self.storage)
        self.insert_srv = RPCServer("127.0.0.1", 0, HELLO_INSERT, handlers)
        self.select_srv = RPCServer("127.0.0.1", 0, HELLO_SELECT, handlers)
        self.insert_srv.start()
        self.select_srv.start()

    def client(self, timeout=10.0):
        from victoriametrics_tpu.parallel.cluster_api import \
            StorageNodeClient
        return StorageNodeClient("127.0.0.1", self.insert_srv.port,
                                 self.select_srv.port, timeout=timeout)

    def stop(self):
        self.insert_srv.stop()
        self.select_srv.stop()
        self.storage.close()


@pytest.fixture()
def two_nodes(tmp_path):
    nodes = [_Node(tmp_path / "n0"), _Node(tmp_path / "n1")]
    yield nodes
    for n in nodes:
        try:
            n.stop()
        except Exception:
            pass


def _seed(cluster, n_series=24):
    rows = [({"__name__": "tg", "idx": str(i)}, T0 + j * 15_000,
             float(i * 10 + j)) for i in range(n_series) for j in range(4)]
    cluster.add_rows(rows)
    return rows


def _filters():
    from victoriametrics_tpu.storage.tag_filters import filters_from_dict
    return filters_from_dict({"__name__": "tg"})


class TestDeadlineRPC:
    def test_stalled_node_costs_one_deadline_not_timeout(self, two_nodes):
        """The acceptance property: with a 0.6s query deadline and a
        10s RPC default timeout, a stalled storage node costs the query
        ~its deadline — not the 10s per-hop default — and the surviving
        node's rows come back partial."""
        cluster = ClusterStorage([n.client(timeout=10.0)
                                  for n in two_nodes])
        _seed(cluster)
        cluster.reset_partial()
        full = cluster.search_columns(_filters(), T0, T0 + 60_000)
        assert full.n_series == 24 and not cluster.last_partial
        # node 1's select plane replaced by a handshake-then-hang server
        # (the SIGSTOP shape: TCP-alive, never answers)
        stalled = _StallWrapper(two_nodes[1])
        try:
            cluster2 = ClusterStorage([two_nodes[0].client(timeout=10.0),
                                       stalled.client(timeout=10.0)])
            cluster2.reset_partial()
            t0 = time.perf_counter()
            cols = cluster2.search_columns(
                _filters(), T0, T0 + 60_000,
                deadline=time.monotonic() + 0.6)
            took = time.perf_counter() - t0
            assert took < 5.0, f"stall cost {took:.1f}s (per-hop timeout?)"
            assert cluster2.last_partial
            assert 0 < cols.n_series < 24
        finally:
            stalled.stop()

    def test_killed_node_partial_with_surviving_exact_rows(self,
                                                           two_nodes):
        """Killed node mid-life: the scatter-gather yields partial=True
        and EXACTLY the surviving node's rows (same names, timestamps
        and values as querying that node directly)."""
        cluster = ClusterStorage([n.client() for n in two_nodes])
        _seed(cluster)
        cluster.reset_partial()
        before = cluster.search_columns(_filters(), T0, T0 + 60_000)
        assert before.n_series == 24
        # the surviving node's own truth, fetched before the kill
        survivor = two_nodes[0].storage.search_columns(
            _filters(), T0, T0 + 60_000)
        two_nodes[1].stop()
        # an in-process server stop leaves established connections alive
        # (daemon handler threads); sever them like the process death
        # would, so the next call must re-dial the closed listener
        cluster.nodes[1].close()
        cluster.reset_partial()
        cols = cluster.search_columns(_filters(), T0, T0 + 60_000)
        assert cluster.last_partial is True
        assert cols.raw_names == survivor.raw_names
        np.testing.assert_array_equal(cols.counts, survivor.counts)
        sel = np.arange(cols.ts.shape[1])[None, :] < cols.counts[:, None]
        sel2 = np.arange(survivor.ts.shape[1])[None, :] < \
            survivor.counts[:, None]
        np.testing.assert_array_equal(cols.ts[sel], survivor.ts[sel2])
        np.testing.assert_array_equal(cols.vals[sel], survivor.vals[sel2])

    def test_dripping_stream_costs_one_deadline(self):
        """A degraded node emitting each streamed frame just inside the
        per-op timeout must still cost at most ONE deadline: the client
        re-checks the budget between frames (and tears the connection
        down so the half-read stream can't poison the next pooled
        call)."""
        def h_drip(r):
            from victoriametrics_tpu.parallel.rpc import Writer as W
            for i in range(50):
                time.sleep(0.12)
                yield W().u64(i)
        srv = RPCServer("127.0.0.1", 0, HELLO_SELECT,
                        {"drip_v1": h_drip})
        srv.start()
        try:
            c = RPCClient("127.0.0.1", srv.port, HELLO_SELECT,
                          timeout=10.0)
            t0 = time.perf_counter()
            with pytest.raises(RPCDeadlineError):
                c.call_stream("drip_v1", Writer(),
                              deadline=time.monotonic() + 0.4)
            took = time.perf_counter() - t0
            assert took < 2.0, f"dripping stream ran {took:.1f}s"
        finally:
            srv.stop()

    def test_connect_respects_deadline_on_dead_port(self):
        """Connection establishment against a dead/blackholed peer is
        bounded by the caller's deadline, not the constructor timeout."""
        import socket as _socket
        # a bound-but-unaccepting listener: connects hang in the backlog
        lst = _socket.socket()
        lst.bind(("127.0.0.1", 0))
        lst.listen(0)
        port = lst.getsockname()[1]
        # saturate the backlog so further connects block
        burners = []
        for _ in range(64):
            s = _socket.socket()
            s.setblocking(False)
            try:
                s.connect_ex(("127.0.0.1", port))
            except OSError:
                pass
            burners.append(s)
        try:
            c = RPCClient("127.0.0.1", port, HELLO_SELECT, timeout=30.0)
            t0 = time.perf_counter()
            with pytest.raises((RPCError, OSError)):
                c.call("x_v1", Writer(),
                       deadline=time.monotonic() + 0.4)
            assert time.perf_counter() - t0 < 5.0
        finally:
            for s in burners:
                s.close()
            lst.close()

    def test_select_connections_do_not_serialize(self, two_nodes):
        """The select plane pools connections (RPCClientPool): two
        concurrent 400ms searches against ONE node must overlap instead
        of queueing on a single TCP connection (which would also hide
        concurrent load from the node-side TenantGate)."""
        client = two_nodes[0].client()
        seeded = ClusterStorage([n.client() for n in two_nodes])
        _seed(seeded)
        faultinject.configure("rpc:searchColumns_v1=delay:400")
        done = []

        def one():
            t0 = time.perf_counter()
            client.search_columns(_filters(), T0, T0 + 60_000)
            done.append(time.perf_counter() - t0)

        threads = [threading.Thread(target=one) for _ in range(2)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        wall = time.perf_counter() - t0
        faultinject.configure("")
        assert len(done) == 2
        # serialized would be >= 800ms; pooled overlaps in ~400ms
        assert wall < 0.7, f"concurrent selects serialized: {wall:.2f}s"

    def test_shed_load_crosses_rpc_boundary_as_itself(self, two_nodes,
                                                      monkeypatch):
        """A remote TenantGate rejection must arrive at the vmselect
        side AS a SearchLimitError (→ 429 for that tenant only), not as
        a generic node failure that would mark the healthy node down
        and serve every other tenant partial results."""
        monkeypatch.setenv("VM_TENANT_QUOTAS", "9:0=1:50")
        seeded = ClusterStorage([n.client() for n in two_nodes])
        _seed(seeded)
        # single-node cluster: the in-process "nodes" share one
        # process-global gate, so the holder takes tenant 9's slot
        # directly through the storage engine and the probe goes over
        # the wire against one node
        cluster = ClusterStorage([two_nodes[0].client()])
        faultinject.configure("storage:search:9:0=delay:600")
        holder = threading.Thread(
            target=lambda: two_nodes[0].storage.search_columns(
                _filters(), T0, T0 + 60_000, tenant=(9, 0)))
        holder.start()
        time.sleep(0.2)
        from victoriametrics_tpu.utils import metrics as metricslib
        errs = metricslib.REGISTRY.counter(
            'vm_rpc_server_errors_total{method="searchColumns_v1"}')
        errs_before = errs.get()
        with pytest.raises(SearchLimitError):
            cluster.search_columns(_filters(), T0, T0 + 60_000,
                                   tenant=(9, 0))
        holder.join(timeout=10)
        faultinject.configure("")
        # shed load is by design: it must not read as a server ERROR
        # (own counter vm_rpc_server_shed_total instead)
        assert errs.get() == errs_before
        # the node was never at fault: still healthy, and another
        # tenant's query through it is complete, not partial
        assert all(n.healthy for n in cluster.nodes)
        cluster.reset_partial()
        cols = cluster.search_columns(_filters(), T0, T0 + 60_000)
        assert cols.n_series > 0 and not cluster.last_partial

    def test_exhausted_deadline_does_not_mark_nodes_down(self,
                                                         two_nodes):
        """A query whose budget was gone before any I/O is the QUERY's
        failure: it errors, but must not poison node health for the
        next 2s of other queries."""
        cluster = ClusterStorage([n.client() for n in two_nodes])
        _seed(cluster)
        with pytest.raises(RPCError):
            cluster.search_columns(_filters(), T0, T0 + 60_000,
                                   deadline=time.monotonic() - 1.0)
        assert all(n.healthy for n in cluster.nodes)
        cluster.reset_partial()
        cols = cluster.search_columns(_filters(), T0, T0 + 60_000)
        assert cols.n_series == 24 and not cluster.last_partial

    def test_backoff_retry_recovers_from_resets(self, two_nodes,
                                                monkeypatch):
        """The bounded-backoff reconnect path: with injected connection
        resets at 30%, calls still succeed (within the retry budget)
        and vm_rpc_retries_total advances."""
        from victoriametrics_tpu.utils import metrics as metricslib
        monkeypatch.setenv("VM_RPC_RETRIES", "4")
        monkeypatch.setenv("VM_RPC_BACKOFF_MS", "5")
        cluster = ClusterStorage([n.client() for n in two_nodes])
        _seed(cluster)
        retries = metricslib.REGISTRY.counter("vm_rpc_retries_total")
        before = retries.get()
        faultinject.configure("rpc:searchColumns_v1=reset::0.3")
        ok = 0
        for _ in range(10):
            cluster.reset_partial()
            try:
                cols = cluster.search_columns(_filters(), T0, T0 + 60_000)
                ok += cols.n_series == 24 and not cluster.last_partial
            except RPCError:
                pass
        faultinject.configure("")
        assert ok >= 7, f"only {ok}/10 full results under 30% resets"
        assert retries.get() > before


class _StallWrapper:
    """A fake storage node whose select server accepts the handshake
    and then never answers any call (the SIGSTOP shape, in-process)."""

    def __init__(self, real_node):
        def h_stall(r):
            time.sleep(300)
        handlers = {m: h_stall for m in
                    ("searchColumns_v1", "search_v1")}
        self.select_srv = RPCServer("127.0.0.1", 0, HELLO_SELECT, handlers)
        self.select_srv.start()
        self.insert_port = real_node.insert_srv.port

    def client(self, timeout=10.0):
        from victoriametrics_tpu.parallel.cluster_api import \
            StorageNodeClient
        return StorageNodeClient("127.0.0.1", self.insert_port,
                                 self.select_srv.port, timeout=timeout)

    def stop(self):
        self.select_srv.stop()


# ---------------------------------------------------------------------------
# HTTP surfaces: 429 shed load, deny_partial 503, slow-log linkage
# ---------------------------------------------------------------------------

class _ShedStorage:
    """Stub storage whose every search is shed by the gate."""

    last_partial = False

    def search_columns(self, *a, **kw):
        raise SearchLimitError("couldn't start the search: test shed")

    def search_series(self, *a, **kw):
        raise SearchLimitError("couldn't start the search: test shed")

    def metrics(self):
        return {}


class _PartialStorage:
    """Stub storage returning an empty-but-partial scatter-gather."""

    last_partial = True

    def reset_partial(self):
        # sticky: simulates a fanout that keeps seeing a dead node
        self.last_partial = True

    def search_columns(self, *a, **kw):
        from victoriametrics_tpu.storage.columnar import ColumnarSeries
        return ColumnarSeries.empty()

    def search_series(self, *a, **kw):
        return []

    def metrics(self):
        return {}


def _api(storage):
    from victoriametrics_tpu.httpapi.prometheus_api import PrometheusAPI
    from victoriametrics_tpu.httpapi.server import HTTPServer
    srv = HTTPServer("127.0.0.1", 0)
    api = PrometheusAPI(storage)
    api.register(srv, mode="all")
    srv.start()
    return srv, api


class TestShedLoadHTTP:
    def test_gate_rejection_is_429_with_retry_after(self):
        from tests.apptest_helpers import Client
        srv, api = _api(_ShedStorage())
        try:
            c = Client(srv.port)
            code, body = c.get("/api/v1/query", query="up",
                               time=str(T0 // 1000))
            assert code == 429
            res = json.loads(body)
            assert res["errorType"] == "too_many_requests"
            # rejected queries are linked into the slow-query log
            code, body = c.get("/api/v1/status/slow_queries")
            recs = json.loads(body)["data"]
            assert any(r.get("rejected") and r["query"] == "up"
                       for r in recs), recs
        finally:
            srv.stop()

    def test_faults_endpoint_is_opt_in(self, monkeypatch):
        """/internal/faults must not let an unauthenticated client
        stall a production process: 403 unless the process opted into
        chaos (VM_FAULT_INJECT=1 / VM_FAULTS)."""
        from tests.apptest_helpers import Client
        monkeypatch.delenv("VM_FAULT_INJECT", raising=False)
        srv, api = _api(_PartialStorage())
        try:
            c = Client(srv.port)
            code, _ = c.get("/internal/faults", set="rpc:*=stall")
            assert code == 403
            assert not faultinject.active()
            monkeypatch.setenv("VM_FAULT_INJECT", "1")
            code, body = c.get("/internal/faults",
                               set="rpc:x_v1=delay:5")
            assert code == 200
            assert json.loads(body)["faults"] == "rpc:x_v1=delay:5"
            code, _ = c.get("/internal/faults", clear="1")
            assert code == 200 and not faultinject.active()
        finally:
            srv.stop()

    def test_rejection_visible_in_flight_capture(self):
        """The gate:rejected instant lands in the flight ring, so an
        on-demand capture explains shed load at /status/flight."""
        from victoriametrics_tpu.utils import flightrec
        if not flightrec.enabled():
            pytest.skip("flight recorder disabled")
        gate = TenantGate(limit=1, max_queue_ms=20, quotas={})
        with gate.admit((0, 0)):
            with pytest.raises(SearchLimitError):
                with gate.admit((0, 0)):
                    pass
        cap = flightrec.RECORDER.capture("test_shed")
        events = [e for e in cap["trace"]["traceEvents"]
                  if e.get("name") == "gate:rejected"]
        assert events, "gate:rejected instant missing from capture"


class TestDenyPartial:
    def test_partial_counts_and_deny_flag_503(self, monkeypatch):
        from tests.apptest_helpers import Client
        from victoriametrics_tpu.utils import metrics as metricslib
        ctr = metricslib.REGISTRY.counter("vm_partial_results_total")
        srv, api = _api(_PartialStorage())
        try:
            c = Client(srv.port)
            before = ctr.get()
            # default: partial served as isPartial=true 200
            code, body = c.get("/api/v1/query", query="up",
                               time=str(T0 // 1000))
            assert code == 200
            assert json.loads(body)["isPartial"] is True
            assert ctr.get() == before + 1
            # request flag: partial becomes a 503
            code, body = c.get("/api/v1/query", query="up",
                               time=str(T0 // 1000), deny_partial="1")
            assert code == 503
            assert json.loads(body)["errorType"] == "unavailable"
            # env default, overridable per request
            monkeypatch.setenv("VM_DENY_PARTIAL_RESPONSE", "1")
            code, _ = c.get("/api/v1/query", query="up",
                            time=str(T0 // 1000))
            assert code == 503
            code, _ = c.get("/api/v1/query", query="up",
                            time=str(T0 // 1000), deny_partial="0")
            assert code == 200
            # query_range path too
            monkeypatch.delenv("VM_DENY_PARTIAL_RESPONSE")
            code, body = c.get("/api/v1/query_range", query="up",
                               start=str(T0 // 1000),
                               end=str(T0 // 1000 + 600), step="15",
                               deny_partial="1", nocache="1")
            assert code == 503
        finally:
            srv.stop()
