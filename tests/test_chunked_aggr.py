"""Bounded-memory chunked aggregation (the tmp-blocks-spool +
incremental-aggregation pairing): storage.search_columns_chunked yields
disjoint bounded chunks, and _try_host_chunked_aggr must produce results
IDENTICAL to the normal full-fetch path for every supported aggregator."""

import numpy as np
import pytest

from victoriametrics_tpu import native
from victoriametrics_tpu.query.exec import exec_query
from victoriametrics_tpu.query.types import EvalConfig
from victoriametrics_tpu.storage.storage import Storage
from victoriametrics_tpu.storage.tag_filters import filters_from_dict

T0 = 1_753_700_000_000
NS, NN = 220, 180


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("chunked")
    s = Storage(str(tmp / "s"))
    rng = np.random.default_rng(7)
    keys = [f'chm{{idx="{i}",grp="g{i % 5}"}}'.encode() for i in range(NS)]
    keybuf = b"".join(keys)
    klens = np.fromiter((len(k) for k in keys), np.int64, NS)
    koffs = np.concatenate([[0], np.cumsum(klens)[:-1]])
    base = np.arange(NN, dtype=np.int64) * 15_000 + T0
    ts2 = np.sort(base[None, :] + rng.integers(-2000, 2001, (NS, NN)),
                  axis=1)
    vals2 = np.cumsum(rng.integers(0, 50, (NS, NN)), axis=1) \
        .astype(np.float64)
    # sprinkle gaps (NaN-free storage; gaps via missing samples handled
    # by jitter) and a gauge-style series set
    s.add_rows_columnar(native.ColumnarRows(
        keybuf, np.repeat(koffs, NN), np.repeat(klens, NN),
        ts2.reshape(-1), vals2.reshape(-1)))
    s.force_flush()
    yield s
    s.close()


pytestmark = pytest.mark.skipif(not native.available(),
                                reason="needs native lib")


class TestChunkedFetch:
    def test_chunks_are_disjoint_and_complete(self, store):
        filters = filters_from_dict({"__name__": "chm"})
        seen = {}
        n_chunks = 0
        for cols in store.search_columns_chunked(
                filters, T0 - 10**6, T0 + 10**9,
                max_chunk_samples=NN * 37):
            n_chunks += 1
            for i, raw in enumerate(cols.raw_names):
                assert raw not in seen
                n = int(cols.counts[i])
                seen[raw] = (cols.ts[i, :n].copy(), cols.vals[i, :n].copy())
        assert n_chunks > 3  # actually chunked
        assert len(seen) == NS
        full = store.search_columns(filters, T0 - 10**6, T0 + 10**9)
        assert set(seen) == set(full.raw_names)
        for i, raw in enumerate(full.raw_names):
            n = int(full.counts[i])
            np.testing.assert_array_equal(seen[raw][0], full.ts[i, :n])
            np.testing.assert_array_equal(seen[raw][1], full.vals[i, :n])


class TestChunkedAggr:
    @pytest.mark.parametrize("q", [
        'sum by (grp)(rate(chm[2m]))',
        'sum(rate(chm[2m]))',
        'count by (grp)(rate(chm[2m]))',
        'avg by (grp)(increase(chm[2m]))',
        'min by (grp)(chm)',
        'max without (idx)(delta(chm[2m]))',
        # keep_name=False rollup grouped by __name__: the blanked-name
        # semantics must match the normal path (r5 review finding)
        'sum by (__name__)(rate(chm[2m]))',
        'sum by (__name__)(chm)',
    ])
    def test_matches_normal_path(self, store, q, monkeypatch):
        kw = dict(start=T0 + 600_000, end=T0 + (NN - 1) * 15_000,
                  step=60_000, storage=store, tpu=None)
        normal = exec_query(EvalConfig(**kw, disable_cache=True), q)
        monkeypatch.setenv("VM_CHUNKED_AGGR_MIN_BYTES", "0")
        monkeypatch.setenv("VM_CHUNK_FETCH_SAMPLES", str(NN * 31))
        chunked = exec_query(EvalConfig(**kw, disable_cache=True), q)
        dn = {ts.metric_name.marshal(): ts.values for ts in normal}
        dc = {ts.metric_name.marshal(): ts.values for ts in chunked}
        assert set(dn) == set(dc), q
        for k in dn:
            np.testing.assert_array_equal(
                np.isnan(dn[k]), np.isnan(dc[k]), err_msg=q)
            m = ~np.isnan(dn[k])
            np.testing.assert_allclose(dc[k][m], dn[k][m], rtol=1e-9,
                                       err_msg=q)

    def test_not_engaged_for_unsupported_shapes(self, store, monkeypatch):
        """Aggrs outside the accumulator set and non-trivial args keep the
        normal path (and still work)."""
        monkeypatch.setenv("VM_CHUNKED_AGGR_MIN_BYTES", "0")
        kw = dict(start=T0 + 600_000, end=T0 + (NN - 1) * 15_000,
                  step=60_000, storage=store, tpu=None)
        rows = exec_query(EvalConfig(**kw, disable_cache=True),
                          'stddev by (grp)(rate(chm[2m]))')
        assert len(rows) == 5

    def test_memory_bounded(self, store, monkeypatch):
        """The chunked path must never materialize the full (S, N)
        matrix: assert peak extra allocation stays near one chunk."""
        import victoriametrics_tpu.storage.storage as stmod
        monkeypatch.setenv("VM_CHUNKED_AGGR_MIN_BYTES", "0")
        monkeypatch.setenv("VM_CHUNK_FETCH_SAMPLES", str(NN * 16))
        peak = {"series": 0}
        orig = Storage.search_columns

        def spy(self, *a, **k):
            cols = orig(self, *a, **k)
            peak["series"] = max(peak["series"], cols.n_series)
            return cols
        monkeypatch.setattr(Storage, "search_columns", spy)
        kw = dict(start=T0 + 600_000, end=T0 + (NN - 1) * 15_000,
                  step=60_000, storage=store, tpu=None)
        rows = exec_query(EvalConfig(**kw, disable_cache=True),
                          'sum by (grp)(rate(chm[2m]))')
        assert len(rows) == 5
        assert 0 < peak["series"] <= 64  # one chunk's series, not all 220
