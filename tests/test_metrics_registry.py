"""Self-observability tests: the central metrics registry (counters /
gauges / vmrange histograms / process_*), exposition round-trip through
the project's own Prometheus text parser, /metrics over HTTP, the
active/top-query status endpoints, pushmetrics label splicing + gzip,
tracer context-manager semantics, and cross-RPC trace propagation on a
2-node cluster."""

import gzip
import json

import pytest

from victoriametrics_tpu.ingest.parsers import parse_prometheus
from victoriametrics_tpu.utils import metrics as metricslib
from victoriametrics_tpu.utils import querytracer
from victoriametrics_tpu.utils.metrics import (MetricsRegistry,
                                               escape_label_value,
                                               format_name,
                                               splice_extra_labels)

try:
    # the storage stack itself is the gate: ops/compress falls back to
    # zlib when the zstandard package is absent, so these run either way
    import victoriametrics_tpu.storage.storage  # noqa: F401
    _STORAGE_ERR = None
except ImportError as e:
    _STORAGE_ERR = e

needs_storage = pytest.mark.skipif(
    _STORAGE_ERR is not None,
    reason=f"storage deps unavailable: {_STORAGE_ERR}")

T0 = 1_753_700_000_000


def parse_exposition(text: str) -> dict:
    """name{sorted labels} -> float value, via the project's own parser."""
    out = {}
    for row in parse_prometheus(text, default_ts=T0):
        labels = dict(row.labels)
        name = labels.pop("__name__")
        key = (name, tuple(sorted(labels.items())))
        out[key] = row.value
    return out


def find_series(parsed: dict, name: str, **label_subset):
    return [(k, v) for k, v in parsed.items()
            if k[0] == name and
            all(dict(k[1]).get(lk) == lv
                for lk, lv in label_subset.items())]


class TestRegistry:
    def test_counter_and_float_counter(self):
        r = MetricsRegistry()
        c = r.counter("t_reqs_total")
        c.inc()
        c.inc(4)
        assert c.get() == 5
        assert r.counter("t_reqs_total") is c  # get-or-create
        fc = r.float_counter("t_secs_total")
        fc.inc(0.25)
        fc.inc(0.5)
        assert fc.get() == 0.75

    def test_gauge_set_and_callback(self):
        r = MetricsRegistry()
        g = r.gauge("t_g")
        g.set(3)
        g.inc()
        g.dec(2)
        assert g.get() == 2
        box = [7]
        cb = r.gauge("t_cb", callback=lambda: box[0])
        assert cb.get() == 7
        box[0] = 9
        assert cb.get() == 9

    def test_type_mismatch_rejected(self):
        r = MetricsRegistry()
        r.counter("t_x")
        with pytest.raises(ValueError):
            r.gauge("t_x")

    def test_invalid_name_rejected(self):
        r = MetricsRegistry()
        with pytest.raises(ValueError):
            r.counter('bad{unclosed="')

    def test_histogram_vmrange_buckets(self):
        from victoriametrics_tpu.query.vmhistogram import vmrange_for
        r = MetricsRegistry()
        h = r.histogram('t_dur_seconds{path="/q"}')
        for v in (0.0015, 0.0015, 2.5):
            h.update(v)
        h.update(float("nan"))   # skipped
        h.update(-1.0)           # skipped
        assert h.get_count() == 3
        assert h.get_sum() == pytest.approx(2.503)
        # +Inf goes to the upper catch-all (reference behavior), not a crash
        h2 = r.histogram("t_inf_seconds")
        h2.update(float("inf"))
        assert h2.get_count() == 1
        from victoriametrics_tpu.query.vmhistogram import (UPPER_RANGE,
                                                           vmrange_for)
        assert vmrange_for(float("inf")) == UPPER_RANGE
        text = r.write_prometheus(include_process=False)
        parsed = parse_exposition(text)
        b15 = find_series(parsed, "t_dur_seconds_bucket", path="/q",
                          vmrange=vmrange_for(0.0015))
        assert b15 and b15[0][1] == 2.0
        assert find_series(parsed, "t_dur_seconds_sum", path="/q")
        cnt = find_series(parsed, "t_dur_seconds_count", path="/q")
        assert cnt[0][1] == 3.0

    def test_write_prometheus_type_lines_and_roundtrip(self):
        r = MetricsRegistry()
        r.counter("t_a_total").inc(2)
        r.gauge("t_b").set(1.5)
        r.histogram("t_h").update(0.1)
        text = r.write_prometheus(extra={"t_extra_total": 7})
        assert "# TYPE t_a_total counter" in text
        assert "# TYPE t_b gauge" in text
        assert "# TYPE t_h histogram" in text
        assert "# TYPE t_extra_total counter" in text
        parsed = parse_exposition(text)
        assert parsed[("t_a_total", ())] == 2.0
        assert parsed[("t_b", ())] == 1.5
        assert parsed[("t_extra_total", ())] == 7.0
        # process metrics rendered and parseable
        assert find_series(parsed, "process_resident_memory_bytes")
        assert find_series(parsed, "process_num_threads")

    def test_label_escaping_roundtrip(self):
        r = MetricsRegistry()
        tricky = 'sp ace"quote\\slash\nnewline'
        r.counter(format_name("t_esc_total", {"v": tricky})).inc()
        parsed = parse_exposition(r.write_prometheus(
            include_process=False))
        rows = find_series(parsed, "t_esc_total")
        assert rows and dict(rows[0][0][1])["v"] == tricky
        assert escape_label_value('a"b') == 'a\\"b'


class TestSpliceExtraLabels:
    def test_plain_and_labeled(self):
        text = 'm1 42\nm2{x="y"} 7\n'
        out = splice_extra_labels(text, 'job="t"')
        assert 'm1{job="t"} 42' in out
        assert 'm2{job="t",x="y"} 7' in out

    def test_label_value_with_space_and_brace(self):
        # the old partition(" ") surgery split inside the label value
        text = 'm{x="a b}c"} 1\n'
        out = splice_extra_labels(text, 'job="t"')
        assert out == 'm{job="t",x="a b}c"} 1\n'

    def test_comments_kept(self):
        out = splice_extra_labels("# TYPE m counter\nm 1\n", 'a="b"')
        assert out.splitlines()[0] == "# TYPE m counter"


class TestPusherRender:
    def test_gzip_body_with_spliced_labels(self):
        from victoriametrics_tpu.utils.pushmetrics import MetricsPusher
        p = MetricsPusher([], lambda: 'm{x="a b"} 1\n',
                          extra_labels='job="t"')
        body = p._render()
        assert gzip.decompress(body) == b'm{job="t",x="a b"} 1\n'


class TestTracerContextManager:
    def test_closes_on_success_and_is_idempotent(self):
        t = querytracer.Tracer("root")
        with t.new_child("child") as c:
            c.donef("done %d", 3)
        d = t.to_dict()
        assert d["children"][0]["message"] == "child: done 3"

    def test_records_exception(self):
        t = querytracer.Tracer("root")
        with pytest.raises(ValueError):
            with t.new_child("will fail"):
                raise ValueError("boom")
        d = t.to_dict()
        assert "error: boom" in d["children"][0]["message"]

    def test_nop_tracer_contextmanager(self):
        with querytracer.NOP as n:
            assert not n.enabled
        querytracer.NOP.add_remote({"message": "x"})
        assert querytracer.NOP.to_dict() == {}

    def test_from_dict_graft(self):
        t = querytracer.Tracer("local")
        t.add_remote({"duration_msec": 5.0, "message": "remote",
                      "children": [{"duration_msec": 2.0,
                                    "message": "inner"}]})
        d = t.to_dict()
        assert d["children"][0]["message"] == "remote"
        assert d["children"][0]["children"][0]["message"] == "inner"
        assert d["children"][0]["duration_msec"] == 5.0


@pytest.fixture()
def app(tmp_path):
    """In-process vmsingle (same shape as test_vmsingle_http.app)."""
    from victoriametrics_tpu.apps.vmsingle import build, parse_flags

    from tests.apptest_helpers import Client
    args = parse_flags([f"-storageDataPath={tmp_path}/data",
                        "-httpListenAddr=127.0.0.1:0"])
    storage, srv, api = build(args)
    srv.start()
    yield Client(srv.port)
    srv.stop()
    storage.close()


def _ingest(app, name="sm", n=3):
    lines = "".join(f'{name}{{i="{k}"}} {k} {T0 + j * 15_000}\n'
                    for k in range(n) for j in range(20))
    code, _ = app.post("/api/v1/import/prometheus", lines.encode())
    assert code == 204


@needs_storage
class TestMetricsEndpoint:
    def test_exposition_parses_and_has_core_series(self, app):
        _ingest(app)
        # a cacheable range query twice: miss then hit on the rollup
        # result cache, plus a vm_request_duration_seconds sample
        for _ in range(2):
            res = app.query_range("sm", T0 / 1e3,
                                  (T0 + 300_000) / 1e3, 15)
            assert res["status"] == "success"
        code, body = app.get("/metrics")
        assert code == 200
        parsed = parse_exposition(body.decode())
        assert parsed, "empty /metrics"
        # per-path vmrange histogram of the request we just made
        buckets = find_series(parsed, "vm_request_duration_seconds_bucket",
                              path="/api/v1/query_range")
        assert buckets, "no vm_request_duration_seconds vmrange buckets"
        assert all("vmrange" in dict(k[1]) for k, _ in buckets)
        assert find_series(parsed, "vm_request_duration_seconds_count",
                           path="/api/v1/query_range")
        # cache hit/miss pair
        reqs = find_series(parsed, "vm_cache_requests_total",
                           type="promql/rollupResult")
        miss = find_series(parsed, "vm_cache_misses_total",
                           type="promql/rollupResult")
        assert reqs and miss
        assert reqs[0][1] >= miss[0][1]
        # process metrics
        rss = find_series(parsed, "process_resident_memory_bytes")
        assert rss and rss[0][1] > 0
        # legacy app-level counters still exposed
        assert find_series(parsed, "vm_rows_inserted_total")
        # per-path request counters
        assert find_series(parsed, "vm_http_requests_total",
                           path="/api/v1/query_range")

    def test_type_lines_present(self, app):
        code, body = app.get("/metrics")
        text = body.decode()
        assert "# TYPE vm_http_requests_total counter" in text
        assert "# TYPE process_resident_memory_bytes gauge" in text

    def test_active_and_top_queries(self, app):
        _ingest(app)
        app.query("sm", T0 / 1e3)
        app.query("sm", T0 / 1e3)
        code, body = app.get("/api/v1/status/top_queries")
        assert code == 200
        data = json.loads(body)
        top = [e for e in data["topByCount"] if e["query"] == "sm"]
        assert top and top[0]["count"] >= 2
        assert top[0]["sumDurationSeconds"] >= 0
        code, body = app.get("/api/v1/status/active_queries")
        assert code == 200
        assert json.loads(body)["status"] == "ok"


class TestQueryStatsRing:
    def test_ring_evicts_oldest(self):
        from victoriametrics_tpu.query.querystats import QueryStats
        qs = QueryStats(max_records=2)
        qs.record("a", 0, 0.1)
        qs.record("b", 0, 0.1)
        qs.record("c", 0, 0.1)
        got = {e["query"] for e in qs.top(10, "count")}
        assert got == {"b", "c"}  # "a" aged out of the ring

    def test_active_queries_gauge(self):
        from victoriametrics_tpu.query.querystats import ActiveQueries
        a = ActiveQueries()
        qid = a.register("q", 0, 0, 15)
        assert len(a) == 1
        snap = a.snapshot()
        assert snap[0]["query"] == "q" and "duration" in snap[0]
        a.unregister(qid)
        assert len(a) == 0


class TestTracePropagationProtocol:
    """Marshal-level halves of cross-RPC tracing — no sockets, no
    compression, so these run even without the zstandard dep."""

    class _FakeStorage:
        last_partial = False

        def search_series(self, filters, min_ts, max_ts, tenant=(0, 0)):
            return []

        def reset_partial(self):
            pass

    def _search_frames(self, trace_flag: int):
        from victoriametrics_tpu.parallel.cluster_api import \
            make_storage_handlers
        from victoriametrics_tpu.parallel.rpc import Reader, Writer
        handlers = make_storage_handlers(self._FakeStorage())
        w = Writer().u64(0).u64(0)   # tenant
        w.u64(0)                     # no filters
        w.i64(T0).i64(T0 + 1000)
        w.u64(trace_flag)
        return list(handlers["search_v1"](Reader(w.payload())))

    def test_meta_frame_carries_storage_span_tree(self):
        from victoriametrics_tpu.parallel.rpc import Reader
        frames = self._search_frames(trace_flag=1)
        meta = Reader(frames[-1].payload())
        assert meta.u64() == (1 << 32) - 1
        assert meta.u64() == 0  # not partial
        tree = json.loads(meta.bytes_())
        assert tree["message"].startswith("vmstorage search_v1")
        assert tree["children"][0]["message"].startswith("search_series")

    def test_no_trace_flag_means_empty_trace_slot(self):
        """Without the trace flag the meta frame carries an EMPTY trace
        slot followed by the extras dict (cost frame + union ack) — an
        old client's json parse of b"" fails into its existing
        malformed-trace guard, so the old-client behavior is
        unchanged."""
        from victoriametrics_tpu.parallel.rpc import Reader
        frames = self._search_frames(trace_flag=0)
        meta = Reader(frames[-1].payload())
        meta.u64(), meta.u64()
        assert meta.bytes_() == b""  # the empty trace slot
        extras = json.loads(meta.bytes_())
        assert extras["filterUnion"] is True
        assert "samples" in extras["cost"]
        assert meta.remaining == 0

    def test_old_client_without_flag_still_served(self):
        """A request WITHOUT the trailing trace flag (pre-extension
        client) is parsed identically; the response's trace slot stays
        empty."""
        from victoriametrics_tpu.parallel.cluster_api import \
            make_storage_handlers
        from victoriametrics_tpu.parallel.rpc import Reader, Writer
        handlers = make_storage_handlers(self._FakeStorage())
        w = Writer().u64(0).u64(0)
        w.u64(0)
        w.i64(T0).i64(T0 + 1000)
        frames = list(handlers["search_v1"](Reader(w.payload())))
        meta = Reader(frames[-1].payload())
        meta.u64(), meta.u64()
        assert meta.bytes_() == b""
        assert "filterUnion" in json.loads(meta.bytes_())

    def test_client_grafts_remote_tree(self):
        from victoriametrics_tpu.parallel.cluster_api import \
            StorageNodeClient
        from victoriametrics_tpu.parallel.rpc import Reader, Writer
        remote = {"duration_msec": 4.2, "message": "vmstorage search_v1",
                  "children": [{"duration_msec": 1.0,
                                "message": "search_series: 5 series"}]}
        # OLD-server frame shape: [partial][trace], no extras — the new
        # client must parse it and answer extras=None (degraded cost)
        meta = Writer().u64(1)  # partial flag (count already consumed)
        meta.bytes_(json.dumps(remote).encode())
        qt = querytracer.Tracer("rpc node n1")
        partial, extras = StorageNodeClient._read_meta(
            Reader(meta.payload()), qt)
        assert partial is True
        assert extras is None
        d = qt.to_dict()
        assert d["children"][0]["message"] == "vmstorage search_v1"
        assert d["children"][0]["children"][0]["message"] == \
            "search_series: 5 series"


@needs_storage
class TestClusterObservability:
    def test_storage_node_span_in_query_trace(self, tmp_path):
        """A trace=1 query against a 2-node cluster returns a trace tree
        containing spans generated ON the storage nodes (serialized over
        the search RPC and grafted into the vmselect trace), and the
        select node's /metrics shows RPC client durations."""
        from tests.apptest_helpers import Client
        from tests.test_cluster import StorageNode
        from victoriametrics_tpu.httpapi.prometheus_api import PrometheusAPI
        from victoriametrics_tpu.httpapi.server import HTTPServer
        from victoriametrics_tpu.parallel.cluster_api import ClusterStorage

        nodes = [StorageNode(tmp_path / f"n{i}") for i in range(2)]
        cluster = ClusterStorage([n.client() for n in nodes],
                                 replication_factor=1)
        try:
            rows = []
            for i in range(8):
                for j in range(30):
                    rows.append(({"__name__": "cm", "idx": str(i)},
                                 T0 + j * 15_000, float(i * 100 + j)))
            cluster.add_rows(rows)
            srv = HTTPServer("127.0.0.1", 0)
            PrometheusAPI(cluster).register(srv, mode="select")
            srv.start()
            try:
                c = Client(srv.port)
                code, body = c.get(
                    "/api/v1/query_range", query="cm",
                    start=str(T0 / 1e3), end=str((T0 + 450_000) / 1e3),
                    step="15", trace="1", nocache="1")
                assert code == 200, body
                res = json.loads(body)
                assert res["data"]["result"], "no data from cluster"
                trace = res.get("trace")
                assert trace, "trace=1 returned no trace tree"

                def messages(d):
                    yield d.get("message", "")
                    for ch in d.get("children", ()):
                        yield from messages(ch)

                msgs = list(messages(trace))
                storage_spans = [m for m in msgs
                                 if m.startswith("vmstorage ")]
                assert storage_spans, \
                    f"no storage-node span in trace: {msgs}"
                # both nodes answered -> at least one rpc span per node
                rpc_spans = [m for m in msgs if "node 127.0.0.1" in m]
                assert len(rpc_spans) >= 2, msgs
                # durations survive serialization
                assert all(d.get("duration_msec", 0) >= 0
                           for d in [trace])

                # select-side /metrics: RPC client duration series
                code, body = c.get("/metrics")
                parsed = parse_exposition(body.decode())
                assert find_series(
                    parsed, "vm_rpc_client_call_duration_seconds_count")
                assert find_series(parsed, "vm_rpc_client_calls_total")
            finally:
                srv.stop()
        finally:
            cluster.close()
            for n in nodes:
                n.stop()

    def test_rpc_server_metrics_counted(self, tmp_path):
        """The storage node side counts served RPC calls."""
        from victoriametrics_tpu.storage.tag_filters import \
            filters_from_dict
        from tests.test_cluster import StorageNode

        before = metricslib.REGISTRY.counter(
            'vm_rpc_server_calls_total{method="search_v1"}').get()
        node = StorageNode(tmp_path / "n")
        try:
            client = node.client()
            out, partial = client.search_series(
                filters_from_dict({"__name__": "cm"}), T0, T0 + 1000)
            assert out == [] and partial is False
        finally:
            node.stop()
        after = metricslib.REGISTRY.counter(
            'vm_rpc_server_calls_total{method="search_v1"}').get()
        assert after >= before + 1
