"""Storage engine tests — coverage modeled on the reference's
lib/storage/storage_test.go, index_db_test.go, partition behaviors:
roundtrips through flush/merge/restart, tag-filter search semantics,
deletes, snapshots, dedup, retention."""

import os

import numpy as np
import pytest

from victoriametrics_tpu.storage.block import Block, rows_to_blocks
from victoriametrics_tpu.storage.index_db import IndexDB
from victoriametrics_tpu.storage.mergeset import Table as MsTable
from victoriametrics_tpu.storage.metric_name import MetricName
from victoriametrics_tpu.storage.storage import Storage
from victoriametrics_tpu.storage.tag_filters import TagFilter, filters_from_dict
from victoriametrics_tpu.storage.tsid import TSID, generate_tsid

T0 = 1_753_700_000_000


class TestMetricName:
    def test_marshal_roundtrip(self):
        mn = MetricName.from_dict(
            {"__name__": "http_requests", "job": "api", "instance": "h1:9090"})
        out = MetricName.unmarshal(mn.marshal())
        assert out == mn
        assert out.to_dict()["job"] == "api"

    def test_label_sorting_canonical(self):
        a = MetricName.from_labels([("b", "2"), ("a", "1"), ("__name__", "m")])
        b = MetricName.from_labels([("a", "1"), ("__name__", "m"), ("b", "2")])
        assert a.marshal() == b.marshal()

    def test_escaping_weird_bytes(self):
        mn = MetricName.from_labels(
            [("__name__", b"m\x00etric"), (b"k\x01ey", b"v\x02al\x00ue")])
        out = MetricName.unmarshal(mn.marshal())
        assert out == mn

    def test_empty_value_dropped(self):
        mn = MetricName.from_dict({"__name__": "m", "empty": ""})
        assert mn.labels == []


class TestMergeset:
    def test_add_search_flush_reopen(self, tmp_path):
        p = str(tmp_path / "ms")
        t = MsTable(p)
        items = [f"key{i:05d}".encode() for i in range(1000)]
        t.add_items(items)
        assert list(t.search_prefix(b"key0001")) == \
            [f"key0001{j}".encode() for j in range(10)]
        t.flush_to_disk()
        t.close()
        t2 = MsTable(p)
        assert list(t2.search_prefix(b"key00999")) == [b"key00999"]
        assert t2.has_item(b"key00000")
        assert not t2.has_item(b"nope")
        t2.close()

    def test_dedup_across_parts(self, tmp_path):
        t = MsTable(str(tmp_path / "ms"))
        t.add_items([b"x", b"y"])
        t.flush_to_disk()
        t.add_items([b"x", b"z"])
        assert list(t.iter_from(b"")) == [b"x", b"y", b"z"]
        t.close()

    def test_large_flush_triggers_file_parts(self, tmp_path):
        t = MsTable(str(tmp_path / "ms"))
        for batch in range(5):
            t.add_items([os.urandom(24) for _ in range(40_000)])
        t.flush_to_disk()
        n = sum(1 for _ in t.iter_from(b""))
        assert n == 200_000
        t.close()


class TestBlocks:
    def test_block_roundtrip(self):
        tsid = TSID(1, 2, 3, 4)
        ts = np.arange(100, dtype=np.int64) * 15000 + T0
        vals = np.round(np.random.default_rng(0).uniform(0, 100, 100), 2)
        blk = Block.from_floats(tsid, ts, vals)
        h, td, vd = blk.marshal()
        out = Block.unmarshal(h, td, vd)
        np.testing.assert_array_equal(out.timestamps, ts)
        np.testing.assert_allclose(out.float_values(), vals, rtol=1e-12)
        assert out.tsid == tsid

    def test_rows_split_at_8k(self):
        tsid = TSID(1, 2, 3, 4)
        n = 20_000
        ts = np.arange(n, dtype=np.int64) * 1000 + T0
        vals = np.ones(n)
        blocks = list(rows_to_blocks(tsid, ts, vals))
        assert [b.rows for b in blocks] == [8192, 8192, 3616]


def mk_storage(tmp_path, **kw):
    return Storage(str(tmp_path / "s"), **kw)


def write_sample_data(s, n_series=20, n_samples=50):
    rows = []
    for i in range(n_series):
        mn = {"__name__": "cpu_usage" if i % 2 == 0 else "mem_usage",
              "instance": f"host{i % 5}", "core": str(i)}
        for j in range(n_samples):
            rows.append((mn, T0 + j * 15000, float(i * 1000 + j)))
    s.add_rows(rows)
    return n_series * n_samples


class TestStorage:
    def test_write_search_roundtrip(self, tmp_path):
        s = mk_storage(tmp_path)
        write_sample_data(s)
        res = s.search_series(filters_from_dict({"__name__": "cpu_usage"}),
                              T0, T0 + 10_000_000)
        assert len(res) == 10
        one = [r for r in res if r.metric_name.get_label(b"core") == b"0"][0]
        assert one.timestamps.size == 50
        np.testing.assert_allclose(one.values, np.arange(50.0))
        s.close()

    def test_filters(self, tmp_path):
        s = mk_storage(tmp_path)
        write_sample_data(s)
        f = filters_from_dict({"__name__": "cpu_usage", "instance": "host0"})
        res = s.search_series(f, T0, T0 + 10_000_000)
        assert len(res) == 2  # cores 0 and 10
        # negative filter
        f = filters_from_dict({"__name__": "cpu_usage",
                               "instance": ("!=", "host0")})
        assert len(s.search_series(f, T0, T0 + 10_000_000)) == 8
        # regex
        f = filters_from_dict({"__name__": ("=~", "cpu_.*")})
        assert len(s.search_series(f, T0, T0 + 10_000_000)) == 10
        # regex alternation uses or-values
        f = filters_from_dict({"__name__": ("=~", "cpu_usage|mem_usage")})
        assert len(s.search_series(f, T0, T0 + 10_000_000)) == 20
        s.close()

    def test_persistence_across_reopen(self, tmp_path):
        s = mk_storage(tmp_path)
        write_sample_data(s)
        s.close()
        s2 = mk_storage(tmp_path)
        res = s2.search_series(filters_from_dict({"__name__": "cpu_usage"}),
                               T0, T0 + 10_000_000)
        assert len(res) == 10
        assert res[0].timestamps.size == 50
        s2.close()

    def test_flush_and_merge_preserve_data(self, tmp_path):
        s = mk_storage(tmp_path)
        write_sample_data(s)
        s.force_flush()
        write_sample_data(s)  # duplicates!
        s.force_merge()
        res = s.search_series(filters_from_dict({"__name__": "cpu_usage"}),
                              T0, T0 + 10_000_000)
        # duplicate timestamps collapse at query time
        assert len(res) == 10
        assert res[0].timestamps.size == 50
        s.close()

    def test_label_apis(self, tmp_path):
        s = mk_storage(tmp_path)
        write_sample_data(s)
        assert s.label_names() == ["__name__", "core", "instance"]
        assert s.label_values("instance") == [f"host{i}" for i in range(5)]
        assert s.label_values("__name__") == ["cpu_usage", "mem_usage"]
        assert s.series_count() == 20
        s.close()

    def test_delete_series(self, tmp_path):
        s = mk_storage(tmp_path)
        write_sample_data(s)
        n = s.delete_series(filters_from_dict({"__name__": "mem_usage"}))
        assert n == 10
        assert s.search_series(filters_from_dict({"__name__": "mem_usage"}),
                               T0, T0 + 10_000_000) == []
        # survives merge and reopen
        s.force_merge()
        s.close()
        s2 = mk_storage(tmp_path)
        assert s2.search_series(filters_from_dict({"__name__": "mem_usage"}),
                                T0, T0 + 10_000_000) == []
        assert len(s2.search_series(filters_from_dict({"__name__": "cpu_usage"}),
                                    T0, T0 + 10_000_000)) == 10
        s2.close()

    def test_snapshot_restore(self, tmp_path):
        s = mk_storage(tmp_path)
        write_sample_data(s)
        name = s.create_snapshot()
        assert name in s.list_snapshots()
        snap = os.path.join(s.snapshots_dir(), name)
        s.close()
        # "restore": open a storage rooted at the snapshot layout
        dst = tmp_path / "restored"
        os.makedirs(dst)
        os.rename(os.path.join(snap, "data"), dst / "data")
        os.rename(os.path.join(snap, "indexdb"), dst / "indexdb")
        os.rename(os.path.join(snap, "format.json"), dst / "format.json")
        s2 = Storage(str(dst))
        res = s2.search_series(filters_from_dict({"__name__": "cpu_usage"}),
                               T0, T0 + 10_000_000)
        assert len(res) == 10
        s2.close()

    def test_dedup_interval(self, tmp_path):
        s = mk_storage(tmp_path, dedup_interval_ms=60_000)
        rows = [({"__name__": "m"}, T0 + i * 15_000, float(i))
                for i in range(40)]
        s.add_rows(rows)
        res = s.search_series(filters_from_dict({"__name__": "m"}),
                              T0, T0 + 10_000_000)
        # 40 samples @15s -> one survivor per occupied 60s bucket
        want = len({(T0 + i * 15_000) // 60_000 for i in range(40)})
        assert res[0].timestamps.size == want
        # each survivor is the last sample of its bucket
        assert res[0].values[0] == 2.0
        s.close()

    def test_stale_nan_roundtrip(self, tmp_path):
        from victoriametrics_tpu.ops import decimal as dec
        s = mk_storage(tmp_path)
        s.add_rows([({"__name__": "m"}, T0, 5.0),
                    ({"__name__": "m"}, T0 + 1000, dec.STALE_NAN)])
        s.force_flush()
        res = s.search_series(filters_from_dict({"__name__": "m"}),
                              T0, T0 + 10_000)
        assert dec.is_stale_nan(res[0].values[1:2]).all()
        s.close()

    def test_multi_month_partitions(self, tmp_path):
        s = mk_storage(tmp_path)
        month = 31 * 86_400_000
        s.add_rows([({"__name__": "m"}, T0, 1.0),
                    ({"__name__": "m"}, T0 + month, 2.0),
                    ({"__name__": "m"}, T0 + 2 * month, 3.0)])
        s.force_flush()
        assert len(s.table.partition_names) == 3
        res = s.search_series(filters_from_dict({"__name__": "m"}),
                              T0, T0 + 3 * month)
        assert res[0].values.tolist() == [1.0, 2.0, 3.0]
        # partial range hits only overlapping partitions
        res = s.search_series(filters_from_dict({"__name__": "m"}),
                              T0 + month, T0 + month)
        assert res[0].values.tolist() == [2.0]
        s.close()

    def test_retention_drops_old_partitions(self, tmp_path):
        s = mk_storage(tmp_path, retention_ms=40 * 86_400_000)
        import time as _t
        now = int(_t.time() * 1e3)
        s.add_rows([({"__name__": "m"}, now - 100 * 86_400_000, 1.0),
                    ({"__name__": "m"}, now, 2.0)])
        s.force_flush()
        assert len(s.table.partition_names) >= 2
        dropped = s.enforce_retention()
        assert dropped >= 1
        res = s.search_series(filters_from_dict({"__name__": "m"}),
                              now - 200 * 86_400_000, now)
        assert res[0].values.tolist() == [2.0]
        s.close()

    def test_flock_exclusive(self, tmp_path):
        s = mk_storage(tmp_path)
        with pytest.raises(RuntimeError, match="locked"):
            Storage(str(tmp_path / "s"))
        s.close()

    def test_tsdb_status(self, tmp_path):
        s = mk_storage(tmp_path)
        write_sample_data(s)
        st = s.tsdb_status()
        assert st["totalSeries"] == 20
        top = {e["name"]: e["count"] for e in st["seriesCountByMetricName"]}
        assert top == {"cpu_usage": 10, "mem_usage": 10}
        s.close()

    def test_register_metric_names(self, tmp_path):
        s = mk_storage(tmp_path)
        s.register_metric_names([{"__name__": "registered", "a": "b"}])
        assert s.series_count() == 1
        assert s.label_values("__name__") == ["registered"]
        s.close()


class TestConcurrency:
    def test_concurrent_read_write_with_merges(self, tmp_path):
        """Regression: thread-unsafe shared zstd ctx segfaulted; merges
        closing parts under readers corrupted reads."""
        import threading
        s = mk_storage(tmp_path)
        errs = []

        def writer(tid):
            try:
                for j in range(15):
                    s.add_rows([({"__name__": "conc", "i": str(k),
                                  "t": str(tid)}, T0 + j * 1000, float(j))
                                for k in range(40)])
                    if j % 5 == 0:
                        s.force_flush()
            except Exception as e:
                errs.append(e)

        def reader():
            try:
                for _ in range(25):
                    s.search_series(filters_from_dict({"__name__": "conc"}),
                                    T0, T0 + 100_000)
            except Exception as e:
                errs.append(e)

        ths = ([threading.Thread(target=writer, args=(i,)) for i in range(2)]
               + [threading.Thread(target=reader) for _ in range(2)])
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert errs == []
        res = s.search_series(filters_from_dict({"__name__": "conc"}),
                              T0, T0 + 100_000)
        assert len(res) == 80
        s.close()


class TestReviewRegressions:
    def test_metric_id_with_zero_bytes_in_tag_scan(self, tmp_path):
        # metric ids whose BE encoding contains 0x00 must parse in value scans
        from victoriametrics_tpu.storage.index_db import IndexDB
        from victoriametrics_tpu.storage.tsid import TSID
        idb = IndexDB(str(tmp_path / "idb"))
        mn = MetricName.from_dict({"__name__": "m", "k": "v"})
        tsid = TSID(1, 2, 3, 256)  # BE bytes contain 0x00 and end 0x01 0x00
        idb.create_indexes_for_metric(mn, tsid)
        vals = list(idb._iter_tag_values(b"k"))
        assert vals == [(b"v", 256)]
        assert idb.label_values("k") == ["v"]
        idb.close()

    def test_regex_group_with_suffix_not_misexpanded(self, tmp_path):
        tf = TagFilter(b"x", b"(a|b)c", regex=True)
        assert tf.or_values is None  # falls back to real regex
        assert tf.match_value(b"ac") and tf.match_value(b"bc")
        assert not tf.match_value(b"a|bc")

    def test_label_apis_time_scoped(self, tmp_path):
        s = mk_storage(tmp_path)
        day = 86_400_000
        s.add_rows([({"__name__": "old", "gen": "0"}, T0 - 30 * day, 1.0),
                    ({"__name__": "new", "gen": "1"}, T0, 2.0)])
        s.force_flush()
        assert s.label_values("__name__", T0 - 3600_000, T0) == ["new"]
        assert set(s.label_values("__name__")) == {"new", "old"}
        assert "gen" in s.label_names(T0 - 3600_000, T0)
        s.close()

    def test_listed_unopenable_part_quarantined_and_restorable(
            self, tmp_path):
        """A listed part that fails to open is QUARANTINED (moved aside,
        bytes preserved, results flagged partial) — never rmtree'd and
        never silently dropped; the operator can restore it by moving it
        back and re-listing it in parts.json."""
        s = mk_storage(tmp_path)
        write_sample_data(s, n_series=2, n_samples=3)
        s.force_flush()
        s.close()
        import glob, json
        parts = glob.glob(str(tmp_path / "s" / "data" / "*" / "p_*"))
        assert parts
        victim = parts[0]
        pdir = os.path.dirname(victim)
        name = os.path.basename(victim)
        meta = os.path.join(victim, "metadata.json")
        orig = open(meta).read()
        open(meta, "w").write("{broken")
        s2 = mk_storage(tmp_path)
        # moved to quarantine/, bytes intact, served loudly partial
        qpath = os.path.join(pdir, "quarantine", name)
        assert not os.path.isdir(victim)
        assert os.path.isdir(qpath)
        assert s2.last_partial is True
        rep = s2.quarantine_report()
        assert len(rep) == 1 and rep[0]["part"] == name
        s2.close()
        # operator restore: heal metadata, move back, re-list
        open(os.path.join(qpath, "metadata.json"), "w").write(orig)
        os.rename(qpath, victim)
        os.rmdir(os.path.join(pdir, "quarantine"))
        manifest = os.path.join(pdir, "parts.json")
        listed = json.load(open(manifest))["parts"]
        json.dump({"parts": sorted(set(listed) | {name})},
                  open(manifest, "w"))
        s3 = mk_storage(tmp_path)
        assert s3.last_partial is False
        assert len(s3.search_series(filters_from_dict({"__name__": "cpu_usage"}),
                                    T0, T0 + 10_000_000)) == 1
        s3.close()


class TestDedupSemantics:
    """reference lib/storage/dedup.go:30-121 — right-inclusive windows,
    max-value tie-break preferring non-stale (issues 3333, 10196)."""

    def test_exact_multiple_closes_window(self):
        import numpy as np
        from victoriametrics_tpu.storage.dedup import deduplicate
        # a sample at an exact interval multiple belongs to the window
        # ENDING there, not the next one
        ts = np.array([60_000, 120_000, 120_001], dtype=np.int64)
        vals = np.array([1.0, 2.0, 3.0])
        kt, kv = deduplicate(ts, vals, 60_000)
        assert list(kt) == [60_000, 120_000, 120_001]
        # two samples inside (60000, 120000]
        ts = np.array([60_001, 120_000, 180_000], dtype=np.int64)
        vals = np.array([1.0, 2.0, 3.0])
        kt, kv = deduplicate(ts, vals, 60_000)
        assert list(kt) == [120_000, 180_000]
        assert list(kv) == [2.0, 3.0]

    def test_equal_ts_prefers_non_stale(self):
        import numpy as np
        from victoriametrics_tpu.ops import decimal as dec
        from victoriametrics_tpu.storage.dedup import deduplicate
        ts = np.array([100, 100, 100], dtype=np.int64)
        vals = np.array([5.0, 7.0, dec.STALE_NAN])
        kt, kv = deduplicate(ts, vals, 60_000)
        assert kt.size == 1 and kv[0] == 7.0
        # all stale -> stale marker survives
        vals = np.array([dec.STALE_NAN, dec.STALE_NAN], dtype=np.float64)
        kt, kv = deduplicate(ts[:2], vals, 60_000)
        assert dec.is_stale_nan(kv[:1]).all()

    def test_equal_ts_int64_mantissas(self):
        import numpy as np
        from victoriametrics_tpu.ops import decimal as dec
        from victoriametrics_tpu.storage.dedup import deduplicate
        ts = np.array([100, 100], dtype=np.int64)
        vals = np.array([42, dec.V_STALE_NAN], dtype=np.int64)
        kt, kv = deduplicate(ts, vals, 60_000)
        assert kv[0] == 42


class TestQueryPathCaches:
    def test_single_sample_blocks_not_collapsed_by_cache(self, tmp_path):
        # zero-length const payloads share file offsets; the block cache
        # must not return one series' block for another (regression)
        s = mk_storage(tmp_path)
        s.add_rows([({"__name__": "bm", "i": str(i)}, T0 + i * 1000, float(i))
                    for i in range(50)])
        s.force_flush()
        f = filters_from_dict({"__name__": "bm"})
        assert len(s.search_series(f, T0, T0 + 100_000)) == 50
        # second (warm, cache-served) query must see all series too
        assert len(s.search_series(f, T0, T0 + 100_000)) == 50
        s.close()

    def test_posting_cache_hits_and_invalidation(self, tmp_path):
        s = mk_storage(tmp_path)
        s.add_rows([({"__name__": "pc", "i": str(i)}, T0, float(i))
                    for i in range(10)])
        f = filters_from_dict({"__name__": "pc"})
        r1 = s.idb.search_metric_ids(f, T0, T0 + 1000)
        h0 = s.idb.filter_cache_hits
        r2 = s.idb.search_metric_ids(f, T0, T0 + 1000)
        assert s.idb.filter_cache_hits == h0 + 1
        assert (r1 == r2).all()
        # a new series invalidates the cached posting set
        s.add_rows([({"__name__": "pc", "i": "new"}, T0, 1.0)])
        r3 = s.idb.search_metric_ids(f, T0, T0 + 1000)
        assert r3.size == 11
        s.close()


class TestIngestFastPath:
    def test_day_rollover_creates_per_day_indexes(self, tmp_path):
        s = mk_storage(tmp_path)
        day_ms = 86_400_000
        base = (T0 // day_ms) * day_ms
        s.add_rows([({"__name__": "fr", "i": "1"}, base + 1000, 1.0)])
        # same series next day through the fast path
        s.add_rows([({"__name__": "fr", "i": "1"}, base + day_ms + 1000, 2.0)])
        f = filters_from_dict({"__name__": "fr"})
        # per-day index must find it on day 2 alone
        res = s.search_series(f, base + day_ms, base + day_ms + 10_000)
        assert len(res) == 1 and res[0].values[0] == 2.0
        s.close()

    def test_label_order_variants_resolve_same_tsid(self, tmp_path):
        s = mk_storage(tmp_path)
        s.add_rows([([(b"a", b"1"), (b"b", b"2"), (b"", b"lo")], T0, 1.0)])
        s.add_rows([([(b"b", b"2"), (b"a", b"1"), (b"", b"lo")],
                     T0 + 1000, 2.0)])
        res = s.search_series(filters_from_dict({"__name__": "lo"}),
                              T0, T0 + 10_000)
        assert len(res) == 1 and res[0].timestamps.size == 2
        s.close()

    def test_delete_purges_raw_cache(self, tmp_path):
        s = mk_storage(tmp_path)
        s.add_rows([({"__name__": "dp", "i": "1"}, T0, 1.0)])
        f = filters_from_dict({"__name__": "dp"})
        assert s.delete_series(f) == 1
        assert not s._tsid_cache_raw  # tombstoned ids must not linger
        assert len(s.search_series(f, T0, T0 + 10_000)) == 0
        s.close()


class TestInfluxEscapes:
    def test_escaped_tag_and_field_keys(self):
        from victoriametrics_tpu.ingest.parsers import parse_influx
        rows = list(parse_influx(
            'weird\\ m,ta\\,g=va\\=lue fo\\=o=3,value=3.5 123000000'))
        d = {tuple(sorted(r.labels)): (r.timestamp, r.value) for r in rows}
        names = {dict(r.labels)["__name__"] for r in rows}
        assert names == {"weird m_fo=o", "weird m"}
        for r in rows:
            assert dict(r.labels)["ta,g"] == "va=lue"
            assert r.timestamp == 123

    def test_tag_value_with_equals_same_on_both_paths(self):
        from victoriametrics_tpu.ingest.parsers import parse_influx
        fast = list(parse_influx('m,tag=a=b f=1 123000000'))
        # a quote elsewhere forces the slow path for the same tag
        slow = list(parse_influx('m,tag=a=b f=1,s="x" 123000000'))
        assert dict(fast[0].labels)["tag"] == "a=b"
        assert dict(slow[0].labels)["tag"] == "a=b"


class TestRollupBatchNonFinite:
    def test_inf_falls_back(self):
        import numpy as np
        from victoriametrics_tpu.ops import rollup_np
        from victoriametrics_tpu.ops.rollup_np import RollupConfig
        cfg = RollupConfig(start=T0, end=T0 + 120_000, step=60_000,
                           window=120_000)
        series = [(np.array([T0 - 10_000, T0 - 5_000], dtype=np.int64),
                   np.array([np.inf, 2.0]))]
        assert rollup_np.rollup_batch("sum_over_time", series, cfg) is None


class TestMultitenancy:
    """accountID:projectID isolation (lib/auth.Token, search.go:376)."""

    def test_identical_names_fully_isolated(self, tmp_path):
        s = mk_storage(tmp_path)
        t1, t2 = (1, 0), (1, 7)
        s.add_rows([({"__name__": "m", "i": "x"}, T0, 1.0)], tenant=t1)
        s.add_rows([({"__name__": "m", "i": "x"}, T0, 2.0)], tenant=t2)
        s.add_rows([({"__name__": "only1", "i": "y"}, T0, 3.0)], tenant=t1)
        f = filters_from_dict({"__name__": "m"})
        r1 = s.search_series(f, T0 - 1000, T0 + 1000, tenant=t1)
        r2 = s.search_series(f, T0 - 1000, T0 + 1000, tenant=t2)
        r0 = s.search_series(f, T0 - 1000, T0 + 1000)  # default tenant
        assert len(r1) == 1 and r1[0].values[0] == 1.0
        assert len(r2) == 1 and r2[0].values[0] == 2.0
        assert r0 == []
        # label APIs are tenant-scoped
        assert s.label_values("__name__", tenant=t1) == ["m", "only1"]
        assert s.label_values("__name__", tenant=t2) == ["m"]
        assert s.series_count(tenant=t1) == 2
        assert s.series_count(tenant=t2) == 1
        assert s.tenants() == [(1, 0), (1, 7)]
        # delete in one tenant leaves the other intact
        assert s.delete_series(f, tenant=t1) == 1
        assert s.search_series(f, T0 - 1000, T0 + 1000, tenant=t1) == []
        assert len(s.search_series(f, T0 - 1000, T0 + 1000, tenant=t2)) == 1
        s.close()

    def test_tenant_survives_restart(self, tmp_path):
        s = mk_storage(tmp_path)
        s.add_rows([({"__name__": "rt"}, T0, 5.0)], tenant=(9, 9))
        s.close()
        s2 = mk_storage(tmp_path)
        f = filters_from_dict({"__name__": "rt"})
        assert len(s2.search_series(f, T0 - 1000, T0 + 1000,
                                    tenant=(9, 9))) == 1
        assert s2.search_series(f, T0 - 1000, T0 + 1000) == []
        assert (9, 9) in s2.tenants()
        s2.close()


class TestFormatVersionGate:
    def test_old_layout_rejected_clearly(self, tmp_path):
        import json as _json
        root = tmp_path / "s"
        os.makedirs(root / "data")
        with pytest.raises(RuntimeError, match="on-disk format"):
            Storage(str(root))
        # wrong version in the marker also rejected
        import shutil as _sh
        _sh.rmtree(root)
        os.makedirs(root / "data")
        with open(root / "format.json", "w") as f:
            _json.dump({"format_version": 1}, f)
        with pytest.raises(RuntimeError, match="v1"):
            Storage(str(root))


class TestCardinalityLimiters:
    """lib/bloomfilter/limiter.go semantics (storage.go:2136)."""

    def test_hourly_limit_drops_over_budget(self, tmp_path):
        s = Storage(str(tmp_path / "cl"), max_hourly_series=10)
        rows = [({"__name__": "cl", "i": str(i)}, T0, float(i))
                for i in range(25)]
        s.add_rows(rows)
        m = s.metrics()
        assert m["vm_hourly_series_limit_max_series"] == 10
        assert m["vm_hourly_series_limit_current_series"] == 10
        # The bloom filter admits a rare false positive WITHOUT counting it
        # (limiter.go:62 semantics; metric ids are nanotime-seeded so the
        # probe positions differ run to run): every row is either dropped or
        # created a series, and at most a couple of FPs sneak past budget.
        dropped = m["vm_hourly_series_limit_rows_dropped_total"]
        created = s.series_count()
        assert dropped + created == 25
        assert 10 <= created <= 12
        # over-budget series created NO index entries (storage.go:2136
        # ordering: limiter gates index creation, not just data rows)
        assert s.new_series_created == created
        # tracked series keep flowing through the fast path
        n = s.add_rows([({"__name__": "cl", "i": "1"}, T0 + 15_000, 9.0)])
        assert n == 1
        assert s.metrics()["vm_hourly_series_limit_rows_dropped_total"] == \
            dropped
        s.close()

    def test_limiter_rotates(self):
        import time as _t
        from victoriametrics_tpu.storage.cardinality import BloomLimiter
        lim = BloomLimiter(2, rotation_s=3600)
        assert lim.add(1) and lim.add(2) and not lim.add(3)
        lim._bucket -= 1  # simulate the hour rolling over
        assert lim.add(3)  # budget reset
        assert lim.current_series == 1


class TestCachePersistence:
    def test_no_reresolve_storm_after_restart(self, tmp_path):
        s = Storage(str(tmp_path / "cp"))
        rows = [({"__name__": "cp", "i": str(i)}, T0, float(i))
                for i in range(200)]
        s.add_rows(rows)
        s.close()
        s2 = Storage(str(tmp_path / "cp"))
        before = s2.slow_row_inserts
        s2.add_rows([({"__name__": "cp", "i": str(i)}, T0 + 15_000, 1.0)
                     for i in range(200)])
        # every tsid came from the persisted cache: one cache-dict hit per
        # series, zero index lookups for day-known series
        assert s2.slow_row_inserts - before == 0
        assert s2.new_series_created == 0
        f = filters_from_dict({"__name__": "cp"})
        assert len(s2.search_series(f, T0, T0 + 100_000)) == 200
        s2.close()


class TestPerMonthIndex:
    def test_retention_drops_month_index_with_partition(self, tmp_path):
        now_ms = int(__import__("time").time() * 1000)
        old_ms = now_ms - 200 * 86_400_000
        s = Storage(str(tmp_path / "pm"), retention_ms=100 * 86_400_000)
        s.add_rows([({"__name__": "old", "i": "1"}, old_ms, 1.0)])
        s.add_rows([({"__name__": "new", "i": "1"}, now_ms, 2.0)])
        s.force_flush()
        months = os.path.join(str(tmp_path / "pm"), "indexdb", "months")
        assert len(os.listdir(months)) == 2
        dropped = s.enforce_retention()
        assert dropped >= 2  # data partition + month index
        live = os.listdir(months)
        assert len(live) == 1
        # new data still searchable through its per-day index
        f = filters_from_dict({"__name__": "new"})
        assert len(s.search_series(f, now_ms - 1000, now_ms + 1000)) == 1
        f = filters_from_dict({"__name__": "old"})
        assert s.search_series(f, old_ms - 1000, old_ms + 1000) == []
        s.close()
