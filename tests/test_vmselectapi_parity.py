"""Round-5 vmselectapi parity RPCs: tagValueSuffixes,
metricNamesUsageStats, resetMetricNamesStats, searchMetadata
(lib/vmselectapi/server.go:560-584)."""

import numpy as np
import pytest

from victoriametrics_tpu import native
from victoriametrics_tpu.parallel.cluster_api import (ClusterStorage,
                                                      StorageNodeClient,
                                                      make_storage_handlers)
from victoriametrics_tpu.parallel.rpc import (HELLO_INSERT, HELLO_SELECT,
                                              RPCServer)
from victoriametrics_tpu.storage.storage import Storage
from victoriametrics_tpu.storage.tag_filters import filters_from_dict

T0 = 1_753_700_000_000
pytestmark = pytest.mark.skipif(not native.available(),
                                reason="needs native lib")


@pytest.fixture()
def cluster2(tmp_path):
    nodes = []
    for i in range(2):
        st = Storage(str(tmp_path / f"n{i}"))
        h = make_storage_handlers(st)
        isrv = RPCServer("127.0.0.1", 0, HELLO_INSERT, h)
        ssrv = RPCServer("127.0.0.1", 0, HELLO_SELECT, h)
        isrv.start()
        ssrv.start()
        nodes.append((st, isrv, ssrv))
    cluster = ClusterStorage([
        StorageNodeClient("127.0.0.1", i.port, s.port)
        for _, i, s in nodes])
    yield cluster, [st for st, _, _ in nodes]
    cluster.close()
    for st, i, s in nodes:
        i.stop()
        s.stop()
        st.close()


def seed(cluster):
    rows = []
    for name in ("foo.bar.baz", "foo.bar.qux", "foo.other", "top"):
        for i in range(3):
            rows.append(({"__name__": name, "idx": str(i)},
                         T0 + i * 15_000, float(i)))
    cluster.add_rows(rows)


class TestTagValueSuffixes:
    def test_graphite_path_expansion(self, cluster2):
        cluster, _ = cluster2
        seed(cluster)
        # top level: everything before the first dot (+ dot for non-leaf)
        sfx = cluster.tag_value_suffixes("__name__", "", ".")
        assert sfx == ["foo.", "top"]
        sfx = cluster.tag_value_suffixes("__name__", "foo.", ".")
        assert sfx == ["bar.", "other"]
        sfx = cluster.tag_value_suffixes("__name__", "foo.bar.", ".")
        assert sfx == ["baz", "qux"]
        # plain tag keys expand too
        sfx = cluster.tag_value_suffixes("idx", "", ".")
        assert sfx == ["0", "1", "2"]

    def test_single_node_storage(self, tmp_path):
        st = Storage(str(tmp_path / "s"))
        try:
            st.add_rows([({"__name__": "a.b.c", "x": "1"}, T0, 1.0)])
            assert st.tag_value_suffixes("__name__", "") == ["a."]
            assert st.tag_value_suffixes("__name__", "a.") == ["b."]
            assert st.tag_value_suffixes("__name__", "a.b.") == ["c"]
        finally:
            st.close()


class TestNameUsageStats:
    def test_tracks_and_resets_across_cluster(self, cluster2):
        cluster, stores = cluster2
        seed(cluster)
        # two queries touch the foo.* family, one touches top
        for _ in range(2):
            cluster.search_columns(
                filters_from_dict({"__name__": ("=~", "foo\\..*")}),
                T0 - 1000, T0 + 10**6)
        cluster.search_columns(filters_from_dict({"__name__": "top"}),
                               T0 - 1000, T0 + 10**6)
        stats = cluster.metric_names_usage_stats()
        by_name = {x["metricName"]: x["requestsCount"] for x in stats}
        # the cluster merge SUMS per-node counters, so the merged count
        # must equal the per-store totals exactly (how many nodes hold a
        # given name is a sharding accident — don't assert on it)
        per_store: dict[str, int] = {}
        for st in stores:
            for x in st.metric_names_usage_stats(10_000):
                per_store[x["metricName"]] = \
                    per_store.get(x["metricName"], 0) + x["requestsCount"]
        assert by_name == per_store
        assert by_name.get("top", 0) >= 1
        assert by_name.get("foo.other", 0) >= 2
        assert all(x["lastRequestTimestamp"] > 0 for x in stats)
        cluster.reset_metric_names_stats()
        assert cluster.metric_names_usage_stats() == []


class TestSearchMetadata:
    def test_fanout_merge(self, cluster2):
        cluster, stores = cluster2
        stores[0].set_metadata(
            {"m1": {"type": "counter", "help": "h1"}})
        stores[1].set_metadata(
            {"m2": {"type": "gauge", "help": "h2"}})
        md = cluster.search_metadata()
        assert md == {"m1": {"type": "counter", "help": "h1"},
                      "m2": {"type": "gauge", "help": "h2"}}
        assert cluster.search_metadata(metric="m2") == {
            "m2": {"type": "gauge", "help": "h2"}}
