"""Satellite app tests: vmagent (scrape -> remote-write -> vmsingle),
vmalert (rules fire, record, notify), vmauth (routing, auth), vmbackup/
vmrestore roundtrip, vmctl migration, persistent queue crash safety."""

import json
import os
import threading
import time

import numpy as np
import pytest

from tests.apptest_helpers import Client
from victoriametrics_tpu.ingest.persistentqueue import PersistentQueue

T0 = 1_753_700_000_000


@pytest.fixture()
def vmsingle(tmp_path):
    from victoriametrics_tpu.apps.vmsingle import build, parse_flags
    args = parse_flags([f"-storageDataPath={tmp_path}/data",
                        "-httpListenAddr=127.0.0.1:0"])
    storage, srv, api = build(args)
    srv.start()
    yield Client(srv.port), storage
    srv.stop()
    storage.close()


class TestPersistentQueue:
    def test_fifo_roundtrip(self, tmp_path):
        q = PersistentQueue(str(tmp_path / "q"))
        for i in range(100):
            q.put(f"block{i}".encode())
        got = [q.get(0.1) for _ in range(100)]
        assert got == [f"block{i}".encode() for i in range(100)]
        assert q.get(0.05) is None
        q.close()

    def test_survives_restart(self, tmp_path):
        q = PersistentQueue(str(tmp_path / "q"), max_inmemory_blocks=2)
        for i in range(10):
            q.put(f"b{i}".encode())
        assert q.get(0.1) == b"b0"
        q.close()  # spills RAM front to disk
        q2 = PersistentQueue(str(tmp_path / "q"))
        rest = []
        while True:
            b = q2.get(0.05)
            if b is None:
                break
            rest.append(b)
        assert rest == [f"b{i}".encode() for i in range(1, 10)]
        q2.close()

    def test_truncated_tail_skipped(self, tmp_path):
        q = PersistentQueue(str(tmp_path / "q"), max_inmemory_blocks=0)
        q.put(b"good")
        q.close()
        # simulate crash mid-write: append a truncated record
        chunk = [f for f in os.listdir(tmp_path / "q")
                 if f.startswith("chunk_")][0]
        with open(tmp_path / "q" / chunk, "ab") as f:
            f.write(b"\xff\xff\xff\x7f partial")
        q2 = PersistentQueue(str(tmp_path / "q"))
        assert q2.get(0.1) == b"good"
        assert q2.get(0.05) is None
        q2.close()


class TestVMAgent:
    def test_scrape_to_remote_write(self, tmp_path, vmsingle):
        client, storage = vmsingle
        # a fake exporter to scrape
        from victoriametrics_tpu.httpapi.server import HTTPServer, Response
        exporter = HTTPServer("127.0.0.1", 0)
        exporter.route("/metrics", lambda req: Response.text(
            'fake_metric{src="exp"} 42.5\n'))
        exporter.start()
        import yaml

        from victoriametrics_tpu.apps.vmagent import VMAgent
        cfg = yaml.safe_load(f"""
scrape_configs:
- job_name: testjob
  scrape_interval: 1s
  static_configs:
  - targets: ["127.0.0.1:{exporter.port}"]
""")
        agent = VMAgent(cfg, [client.base + "/api/v1/write"],
                        str(tmp_path / "agent"))
        agent.start()
        try:
            deadline = time.time() + 20
            found = False
            while time.time() < deadline:
                res = client.query("fake_metric")
                if res["data"]["result"]:
                    found = True
                    break
                time.sleep(0.5)
            assert found, "scraped metric never arrived at storage"
            r = res["data"]["result"][0]
            assert r["metric"]["job"] == "testjob"
            assert r["metric"]["src"] == "exp"
            assert r["value"][1] == "42.5"
            res = client.query("up")
            assert res["data"]["result"][0]["value"][1] == "1"
            assert agent.target_status()[0]["health"] == "up"
        finally:
            agent.stop()
            exporter.stop()

    def test_queue_buffers_while_remote_down(self, tmp_path):
        from victoriametrics_tpu.apps.vmagent import RemoteWriteCtx
        ctx = RemoteWriteCtx("http://127.0.0.1:1/api/v1/write",
                            str(tmp_path / "q"), flush_interval=0.1)
        ctx.start()
        ctx.push([({"__name__": "m"}, T0, 1.0)])
        time.sleep(0.5)
        assert ctx.queue.pending >= 0  # block parked in queue, no crash
        ctx.stop()


class TestVMAlert:
    def test_alerting_and_recording(self, tmp_path, vmsingle):
        client, storage = vmsingle
        now = time.time()
        # seed data that violates the alert threshold
        rows = [({"__name__": "errs", "job": "api"},
                 int((now - 60 + i * 5) * 1000), 100.0 + i) for i in range(13)]
        storage.add_rows(rows)
        # capture notifier posts
        from victoriametrics_tpu.httpapi.server import HTTPServer, Response
        received = []

        def h_alerts(req):
            received.extend(json.loads(req.body))
            return Response.json({})
        am = HTTPServer("127.0.0.1", 0)
        am.route("/api/v2/alerts", h_alerts)
        am.start()

        import yaml
        rules = tmp_path / "rules.yml"
        rules.write_text(yaml.dump({"groups": [{
            "name": "g", "interval": "1s", "rules": [
                {"alert": "ErrsHigh", "expr": "errs > 50", "for": "0s",
                 "labels": {"severity": "crit"},
                 "annotations": {"summary": "errs on {{ $labels.job }}"}},
                {"record": "job:errs:last", "expr": "sum by (job) (errs)"},
            ]}]}))
        from victoriametrics_tpu.apps.vmalert import build, parse_flags
        args = parse_flags([f"-rule={rules}",
                            f"-datasource.url={client.base}",
                            f"-notifier.url=http://127.0.0.1:{am.port}",
                            f"-remoteWrite.url={client.base}",
                            "-httpListenAddr=127.0.0.1:0"])
        groups, srv = build(args)
        srv.start()
        try:
            groups[0].eval_once(time.time())
            assert received, "no alert notification sent"
            assert received[0]["labels"]["alertname"] == "ErrsHigh"
            assert received[0]["labels"]["severity"] == "crit"
            assert "api" in received[0]["annotations"]["summary"]
            # recording rule result + ALERTS series landed in storage
            res = client.query("job:errs:last")
            assert res["data"]["result"][0]["metric"]["job"] == "api"
            res = client.query("ALERTS")
            assert res["data"]["result"][0]["metric"]["alertstate"] == "firing"
            # rules API
            code, body = Client(srv.port).get("/api/v1/rules")
            data = json.loads(body)["data"]["groups"][0]
            assert data["rules"][0]["state"] == "firing"
        finally:
            srv.stop()
            am.stop()

    def test_pending_state_honors_for(self, vmsingle, tmp_path):
        client, storage = vmsingle
        now = time.time()
        storage.add_rows([({"__name__": "g1m"},
                           int((now - 30 + i * 5) * 1000), 99.0)
                          for i in range(7)])
        import yaml
        rules = tmp_path / "r.yml"
        rules.write_text(yaml.dump({"groups": [{
            "name": "g", "rules": [
                {"alert": "A", "expr": "g1m > 1", "for": "1h"}]}]}))
        from victoriametrics_tpu.apps.vmalert import build, parse_flags
        args = parse_flags([f"-rule={rules}",
                            f"-datasource.url={client.base}",
                            "-httpListenAddr=127.0.0.1:0"])
        groups, srv = build(args)
        groups[0].eval_once(time.time())
        rule = groups[0].rules[0]
        states = [s["state"] for s in rule._active.values()]
        assert states == ["pending"]  # `for` not yet satisfied
        srv.stop()


class TestVMAuth:
    def test_routing_and_auth(self, tmp_path, vmsingle):
        client, storage = vmsingle
        storage.add_rows([({"__name__": "am"}, T0, 3.0)])
        import yaml
        cfg = tmp_path / "auth.yml"
        cfg.write_text(yaml.dump({"users": [
            {"username": "u1", "password": "p1",
             "url_map": [{"src_paths": ["/api/v1/.*"],
                          "url_prefix": client.base}]},
            {"bearer_token": "tok2", "url_prefix": client.base},
        ]}))
        from victoriametrics_tpu.apps.vmauth import build, parse_flags
        args = parse_flags([f"-auth.config={cfg}",
                            "-httpListenAddr=127.0.0.1:0"])
        _auth, srv = build(args)
        srv.start()
        try:
            import base64
            import urllib.request
            base = f"http://127.0.0.1:{srv.port}"
            # no auth -> 401
            try:
                urllib.request.urlopen(base + "/api/v1/labels", timeout=10)
                assert False, "expected 401"
            except urllib.error.HTTPError as e:
                assert e.code == 401
            # basic auth routes through
            req = urllib.request.Request(base + "/api/v1/labels")
            req.add_header("Authorization", "Basic " +
                           base64.b64encode(b"u1:p1").decode())
            with urllib.request.urlopen(req, timeout=10) as r:
                assert json.loads(r.read())["status"] == "success"
            # bearer token user
            req = urllib.request.Request(base + "/api/v1/labels")
            req.add_header("Authorization", "Bearer tok2")
            with urllib.request.urlopen(req, timeout=10) as r:
                assert r.status == 200
            # path outside url_map -> 400 for u1
            req = urllib.request.Request(base + "/other")
            req.add_header("Authorization", "Basic " +
                           base64.b64encode(b"u1:p1").decode())
            try:
                urllib.request.urlopen(req, timeout=10)
                assert False, "expected 400"
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            srv.stop()


class TestBackupRestore:
    def test_roundtrip(self, tmp_path, vmsingle):
        client, storage = vmsingle
        storage.add_rows([({"__name__": "bm", "i": str(i)}, T0 + i * 1000,
                           float(i)) for i in range(50)])
        storage.force_flush()
        snap = storage.create_snapshot()
        snap_dir = os.path.join(storage.snapshots_dir(), snap)
        from victoriametrics_tpu.apps.vmbackup import (FsRemote, backup,
                                                       restore)
        remote = FsRemote(str(tmp_path / "bkp"))
        st = backup(snap_dir, remote)
        assert st["uploaded"] > 0
        # incremental: second run uploads nothing
        st2 = backup(snap_dir, remote)
        assert st2["uploaded"] == 0 and st2["skipped"] == st["uploaded"]
        # restore into a fresh dir and open it
        dst = str(tmp_path / "restored")
        restore(remote, dst)
        from victoriametrics_tpu.storage.storage import Storage
        from victoriametrics_tpu.storage.tag_filters import filters_from_dict
        s2 = Storage(dst)
        res = s2.search_series(filters_from_dict({"__name__": "bm"}),
                               T0, T0 + 100_000)
        assert len(res) == 50
        s2.close()


class TestVMCtl:
    def test_vm_native_migration(self, tmp_path, vmsingle):
        client, storage = vmsingle
        storage.add_rows([({"__name__": "mig", "i": str(i)}, T0, float(i))
                          for i in range(20)])
        # destination vmsingle
        from victoriametrics_tpu.apps.vmsingle import build, parse_flags
        args = parse_flags([f"-storageDataPath={tmp_path}/dst",
                            "-httpListenAddr=127.0.0.1:0"])
        storage2, srv2, _ = build(args)
        srv2.start()
        try:
            from victoriametrics_tpu.apps.vmctl import vm_native
            n = vm_native(client.base, f"http://127.0.0.1:{srv2.port}",
                          "mig")
            assert n == 20
            c2 = Client(srv2.port)
            res = c2.query("count(mig)", T0 / 1e3 + 10)
            assert res["data"]["result"][0]["value"][1] == "20"
        finally:
            srv2.stop()
            storage2.close()


class TestVMAlertTool:
    def test_unittest_pass_and_fail(self, tmp_path):
        import yaml

        from victoriametrics_tpu.apps.vmalert_tool import (
            parse_series_values, run_test_file)
        assert parse_series_values("0+10x3") == [0, 10, 20, 30]
        assert parse_series_values("5x2") == [5, 5, 5]
        rules = tmp_path / "rules.yml"
        rules.write_text(yaml.dump({"groups": [{"name": "g", "rules": [
            {"alert": "High", "expr": "m > 15", "for": "0s",
             "labels": {"sev": "crit"}}]}]}))
        test_ok = tmp_path / "t1.yml"
        test_ok.write_text(yaml.dump({
            "rule_files": ["rules.yml"],
            "tests": [{
                "interval": "1m",
                "input_series": [{"series": 'm{job="x"}',
                                  "values": "0+10x10"}],
                "alert_rule_test": [{
                    "eval_time": "5m", "alertname": "High",
                    "exp_alerts": [{"exp_labels": {"job": "x",
                                                   "sev": "crit"}}]}],
                "metricsql_expr_test": [{
                    "expr": "m", "eval_time": "3m",
                    "exp_samples": [{"value": 30}]}],
            }]}))
        assert run_test_file(str(test_ok)) == []
        test_bad = tmp_path / "t2.yml"
        test_bad.write_text(yaml.dump({
            "rule_files": ["rules.yml"],
            "tests": [{
                "interval": "1m",
                "input_series": [{"series": "m", "values": "0x10"}],
                "alert_rule_test": [{
                    "eval_time": "5m", "alertname": "High",
                    "exp_alerts": [{"exp_labels": {"sev": "crit"}}]}],
            }]}))
        fails = run_test_file(str(test_bad))
        assert fails and "High" in fails[0]


class TestVMAgentDepth:
    """Round-2 scrape depth: staleness markers (scrapework.go:441),
    stream-parse, SD providers, dynamic target sync."""

    def _mk_exporter(self, lines_fn):
        from victoriametrics_tpu.httpapi.server import HTTPServer, Response
        srv = HTTPServer("127.0.0.1", 0)
        srv.route("/metrics", lambda req: Response.text(lines_fn()))
        srv.start()
        return srv

    def test_staleness_on_series_disappearance(self, tmp_path):
        from victoriametrics_tpu.apps.vmagent import ScrapeTarget
        from victoriametrics_tpu.ops import decimal as dec
        state = {"n": 2}
        srv = self._mk_exporter(
            lambda: "".join(f'g{{i="{i}"}} 1\n' for i in range(state["n"])))
        got = []
        t = ScrapeTarget(f"http://127.0.0.1:{srv.port}/metrics",
                         {"job": "j"}, 1000, 5, None, got.extend)
        t._scrape_once()
        assert sum(1 for r in got if r[0].get("__name__") == "g") == 2
        got.clear()
        state["n"] = 1  # one series vanishes
        t._scrape_once()
        stale = [r for r in got if r[0].get("__name__") == "g"
                 and dec.is_stale_nan(np.array([r[2]])).any()]
        assert len(stale) == 1 and stale[0][0]["i"] == "1"
        # scrape failure: everything goes stale
        got.clear()
        srv.stop()
        t._scrape_once()
        stale = [r for r in got if dec.is_stale_nan(np.array([r[2]])).any()]
        assert len(stale) == 1  # the remaining g series
        up = [r for r in got if r[0].get("__name__") == "up"]
        assert up and up[0][2] == 0.0
        # stop() stales the auto metrics even after a failed last scrape
        got.clear()
        t.stop(send_stale=True)
        names = {r[0]["__name__"] for r in got}
        assert names == {"up", "scrape_duration_seconds",
                         "scrape_samples_scraped"}
        assert all(dec.is_stale_nan(np.array([r[2]])).any() for r in got)

    def test_stream_parse_large_body(self):
        from victoriametrics_tpu.apps.vmagent import ScrapeTarget
        body = "".join(f'big{{i="{i}"}} {i}\n' for i in range(60_000))
        assert len(body) > (1 << 20)  # comfortably beyond one read chunk
        srv = self._mk_exporter(lambda: body)
        batches = []
        t = ScrapeTarget(f"http://127.0.0.1:{srv.port}/metrics",
                         {"job": "big"}, 1000, 30, None, batches.append)
        t._scrape_once()
        srv.stop()
        n = sum(1 for b in batches for r in b
                if r[0].get("__name__") == "big")
        assert n == 60_000
        assert len(batches) > 2  # streamed in chunks, not one blob

    def test_kubernetes_and_consul_sd(self):
        import json as _json
        from victoriametrics_tpu.httpapi.server import HTTPServer, Response
        from victoriametrics_tpu.ingest import discovery
        srv = HTTPServer("127.0.0.1", 0)
        pods = {"items": [{
            "metadata": {"name": "p1", "namespace": "ns1",
                         "labels": {"app": "web"}},
            "spec": {"nodeName": "n1",
                     "containers": [{"ports": [{"containerPort": 9100,
                                                "name": "metrics"}]}]},
            "status": {"podIP": "10.0.0.5", "phase": "Running"}}]}
        srv.route("/api/v1/pods", lambda r: Response.json(pods))
        srv.route("/v1/catalog/services",
                  lambda r: Response.json({"web": ["prod"]}))
        srv.route("/v1/health/service/web", lambda r: Response.json([
            {"Node": {"Node": "c1", "Address": "10.1.1.1",
                      "Datacenter": "dc1"},
             "Service": {"Service": "web", "Address": "10.1.1.2",
                         "Port": 8080, "Tags": ["prod"]}}]))
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        k8s = discovery.kubernetes_sd({"api_server": base, "role": "pod"})
        assert k8s == [("10.0.0.5:9100", {
            "__meta_kubernetes_namespace": "ns1",
            "__meta_kubernetes_pod_name": "p1",
            "__meta_kubernetes_pod_ip": "10.0.0.5",
            "__meta_kubernetes_pod_node_name": "n1",
            "__meta_kubernetes_pod_phase": "Running",
            "__meta_kubernetes_pod_label_app": "web",
            "__meta_kubernetes_pod_container_port_number": "9100",
            "__meta_kubernetes_pod_container_port_name": "metrics"})]
        consul = discovery.consul_sd({"server": f"127.0.0.1:{srv.port}"})
        assert consul == [("10.1.1.2:8080", {
            "__meta_consul_service": "web",
            "__meta_consul_node": "c1",
            "__meta_consul_address": "10.1.1.1",
            "__meta_consul_service_address": "10.1.1.2",
            "__meta_consul_service_port": "8080",
            "__meta_consul_tags": ",prod,",
            "__meta_consul_dc": "dc1"})]
        srv.stop()

    def test_http_sd(self):
        from victoriametrics_tpu.httpapi.server import HTTPServer, Response
        from victoriametrics_tpu.ingest import discovery
        seen_auth = []

        def h(r):
            seen_auth.append(r.headers.get("authorization", ""))
            return Response.json([
                {"targets": ["10.0.0.1:9100", "10.0.0.2:9100"],
                 "labels": {"env": "prod"}},
                {"targets": ["10.0.0.3:8080"]}])
        srv = HTTPServer("127.0.0.1", 0)
        srv.route("/sd", h)
        srv.start()
        url = f"http://127.0.0.1:{srv.port}/sd"
        out = discovery.http_sd({"url": url, "bearer_token": "tk"})
        assert seen_auth == ["Bearer tk"]
        assert out == [
            ("10.0.0.1:9100", {"__meta_env": "prod", "__meta_url": url}),
            ("10.0.0.2:9100", {"__meta_env": "prod", "__meta_url": url}),
            ("10.0.0.3:8080", {"__meta_url": url})]
        srv.stop()

    def test_dns_sd(self):
        """Fake UDP DNS server answering SRV (with name compression) and A
        queries; the provider must decode both."""
        import socket
        import struct
        import threading
        from victoriametrics_tpu.ingest import discovery

        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sock.bind(("127.0.0.1", 0))
        port = sock.getsockname()[1]

        def serve():
            for _ in range(4):
                try:
                    data, addr = sock.recvfrom(4096)
                except OSError:
                    return
                qid = data[:2]
                qtype = struct.unpack(">H", data[-4:-2])[0]
                # question section starts at 12; echo it back
                question = data[12:]
                hdr = qid + struct.pack(">HHHHH", 0x8180, 1,
                                        2 if qtype == 33 else 1, 0, 0)
                if qtype == 33:   # two SRV records, target via pointer+label
                    rr = b""
                    for prt, tgt in ((9100, b"\x05node1"),
                                     (9200, b"\x05node2")):
                        # name = pointer to the question name at offset 12
                        rdata = struct.pack(">HHH", 10, 5, prt) + \
                            tgt + b"\xc0\x0c"
                        rr += b"\xc0\x0c" + struct.pack(
                            ">HHIH", 33, 1, 300, len(rdata)) + rdata
                elif qtype == 1:  # one A record
                    rr = b"\xc0\x0c" + struct.pack(
                        ">HHIH", 1, 1, 300, 4) + bytes([10, 1, 2, 3])
                else:
                    continue
                sock.sendto(hdr + question + rr, addr)

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        srv_out = discovery.dns_sd({
            "names": ["_metrics._tcp.example.org"],
            "resolver": f"127.0.0.1:{port}"})
        assert srv_out == [
            ("node1._metrics._tcp.example.org:9100",
             {"__meta_dns_name": "_metrics._tcp.example.org",
              "__meta_dns_srv_record_target":
                  "node1._metrics._tcp.example.org",
              "__meta_dns_srv_record_port": "9100"}),
            ("node2._metrics._tcp.example.org:9200",
             {"__meta_dns_name": "_metrics._tcp.example.org",
              "__meta_dns_srv_record_target":
                  "node2._metrics._tcp.example.org",
              "__meta_dns_srv_record_port": "9200"})]
        a_out = discovery.dns_sd({
            "names": ["web.example.org"], "type": "A", "port": 9090,
            "resolver": f"127.0.0.1:{port}"})
        assert a_out == [("10.1.2.3:9090",
                          {"__meta_dns_name": "web.example.org"})]
        sock.close()

    def test_dns_sd_malformed_response_degrades(self):
        """Garbage datagrams must surface as DiscoveryError (last-known-good
        fallback), never as IndexError killing the SD loop."""
        import socket
        import threading
        import pytest as _pytest
        from victoriametrics_tpu.ingest import discovery
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sock.bind(("127.0.0.1", 0))
        port = sock.getsockname()[1]

        def serve():
            try:
                data, addr = sock.recvfrom(4096)
                sock.sendto(data[:2] + b"\x81\x80\x00\x01\x00\x05", addr)
            except OSError:
                pass
        threading.Thread(target=serve, daemon=True).start()
        with _pytest.raises(discovery.DiscoveryError):
            discovery.dns_sd({"names": ["x.example.org"], "type": "A",
                              "port": 1, "resolver": f"127.0.0.1:{port}"})
        sock.close()

    def test_docker_sd(self, tmp_path):
        from victoriametrics_tpu.httpapi.server import HTTPServer, Response
        from victoriametrics_tpu.ingest import discovery
        containers = [{
            "Id": "abc123", "Names": ["/web-1"], "State": "running",
            "Labels": {"com.example.app": "web"},
            "Ports": [{"PrivatePort": 8080, "PublicPort": 32768,
                       "Type": "tcp"}],
            "NetworkSettings": {"Networks": {
                "bridge": {"IPAddress": "172.17.0.2"}}},
        }, {
            "Id": "def456", "Names": ["/db-1"], "State": "running",
            "Labels": {}, "Ports": [],
            "NetworkSettings": {"Networks": {
                "bridge": {"IPAddress": "172.17.0.3"}}},
        }]
        srv = HTTPServer("127.0.0.1", 0)
        srv.route("/containers/json", lambda r: Response.json(containers))
        srv.start()
        out = discovery.docker_sd(
            {"host": f"tcp://127.0.0.1:{srv.port}", "port": 9323})
        srv.stop()
        assert out[0][0] == "172.17.0.2:8080"
        assert out[0][1]["__meta_docker_container_name"] == "/web-1"
        assert out[0][1]["__meta_docker_container_label_com_example_app"] \
            == "web"
        assert out[0][1]["__meta_docker_port_public"] == "32768"
        assert out[1][0] == "172.17.0.3:9323"  # no ports -> cfg port

    def test_docker_sd_unix_socket(self, tmp_path):
        import http.server
        import socket
        import socketserver
        import threading
        from victoriametrics_tpu.ingest import discovery
        spath = str(tmp_path / "docker.sock")

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                body = (b'[{"Id":"x","Names":["/u1"],"State":"running",'
                        b'"Ports":[{"PrivatePort":80}],"NetworkSettings":'
                        b'{"Networks":{"bridge":{"IPAddress":"10.9.9.9"'
                        b'}}}}]')
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        class UnixHTTP(socketserver.UnixStreamServer):
            pass
        UnixHTTP.allow_reuse_address = True
        usrv = UnixHTTP(spath, H)
        t = threading.Thread(target=usrv.serve_forever, daemon=True)
        t.start()
        try:
            out = discovery.docker_sd({"host": f"unix://{spath}"})
            assert out == [("10.9.9.9:80", {
                "__meta_docker_container_id": "x",
                "__meta_docker_container_name": "/u1",
                "__meta_docker_container_state": "running",
                "__meta_docker_network_name": "bridge",
                "__meta_docker_network_ip": "10.9.9.9",
                "__meta_docker_port_private": "80"})]
        finally:
            usrv.shutdown()
            usrv.server_close()

    def test_gce_sd_with_pagination(self):
        from victoriametrics_tpu.httpapi.server import HTTPServer, Response
        from victoriametrics_tpu.ingest import discovery
        page1 = {"items": [{
            "id": 111, "name": "vm-a", "status": "RUNNING",
            "machineType": ".../machineTypes/e2-small",
            "networkInterfaces": [{
                "networkIP": "10.128.0.2", "network": ".../networks/default",
                "accessConfigs": [{"natIP": "34.1.2.3"}]}],
            "metadata": {"items": [{"key": "team", "value": "infra"}]},
            "tags": {"items": ["metrics"]},
        }], "nextPageToken": "p2"}
        page2 = {"items": [{
            "id": 222, "name": "vm-b", "status": "RUNNING",
            "machineType": ".../machineTypes/e2-micro",
            "networkInterfaces": [{"networkIP": "10.128.0.3",
                                   "network": ".../networks/default"}],
        }]}

        def h(r):
            return Response.json(page2 if r.arg("pageToken") else page1)
        srv = HTTPServer("127.0.0.1", 0)
        srv.route("/compute/v1/projects/pr1/zones/us-a/instances", h)
        srv.start()
        out = discovery.gce_sd({
            "project": "pr1", "zone": "us-a", "port": 9100,
            "api_server": f"http://127.0.0.1:{srv.port}"})
        srv.stop()
        assert [a for a, _ in out] == ["10.128.0.2:9100", "10.128.0.3:9100"]
        m = out[0][1]
        assert m["__meta_gce_instance_name"] == "vm-a"
        assert m["__meta_gce_machine_type"] == "e2-small"
        assert m["__meta_gce_public_ip"] == "34.1.2.3"
        assert m["__meta_gce_metadata_team"] == "infra"
        assert m["__meta_gce_tags"] == ",metrics,"  # separator-wrapped

    def test_azure_sd_with_token_and_nic(self):
        from victoriametrics_tpu.httpapi.server import HTTPServer, Response
        from victoriametrics_tpu.ingest import discovery
        seen = {}

        def token_h(r):
            seen["grant"] = r.arg("grant_type")
            seen["client"] = r.arg("client_id")
            return Response.json({"access_token": "azt"})

        vm_id = ("/subscriptions/s1/resourceGroups/rg1/providers/"
                 "Microsoft.Compute/virtualMachines/vm1")
        nic_id = ("/subscriptions/s1/resourceGroups/rg1/providers/"
                  "Microsoft.Network/networkInterfaces/nic1")
        vms = {"value": [{
            "id": vm_id, "name": "vm1", "location": "westeurope",
            "tags": {"env": "prod"},
            "properties": {
                "storageProfile": {"osDisk": {"osType": "Linux"}},
                "networkProfile": {"networkInterfaces": [{"id": nic_id}]},
            }}]}
        nic = {"properties": {"ipConfigurations": [
            {"properties": {"privateIPAddress": "10.2.3.4"}}]}}

        def vms_h(r):
            seen["auth"] = r.headers.get("authorization", "")
            return Response.json(vms)
        srv = HTTPServer("127.0.0.1", 0)
        srv.route("/token", token_h)
        srv.route("/subscriptions/s1/providers/Microsoft.Compute/"
                  "virtualMachines", vms_h)
        srv.route(nic_id, lambda r: Response.json(nic))
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        out = discovery.azure_sd({
            "subscription_id": "s1", "client_id": "cid",
            "client_secret": "cs", "tenant_id": "t1", "port": 9100,
            "api_server": base, "token_url": f"{base}/token"})
        srv.stop()
        assert seen["grant"] == "client_credentials"
        assert seen["auth"] == "Bearer azt"
        assert out[0][0] == "10.2.3.4:9100"
        m = out[0][1]
        assert m["__meta_azure_machine_name"] == "vm1"
        assert m["__meta_azure_machine_resource_group"] == "rg1"
        assert m["__meta_azure_machine_os_type"] == "Linux"
        assert m["__meta_azure_machine_tag_env"] == "prod"
        assert m["__meta_azure_machine_private_ip"] == "10.2.3.4"

    def test_ec2_sd_with_sigv4(self):
        from victoriametrics_tpu.httpapi.server import HTTPServer, Response
        from victoriametrics_tpu.ingest import discovery
        seen = {}
        xml = """<?xml version="1.0"?>
<DescribeInstancesResponse xmlns="http://ec2.amazonaws.com/doc/2013-10-15/">
 <reservationSet><item><instancesSet><item>
  <instanceId>i-123</instanceId><instanceType>t3.micro</instanceType>
  <privateIpAddress>172.1.2.3</privateIpAddress>
  <instanceState><name>running</name></instanceState>
  <placement><availabilityZone>us-east-1a</availabilityZone></placement>
  <tagSet><item><key>Name</key><value>api</value></item></tagSet>
 </item></instancesSet></item></reservationSet>
</DescribeInstancesResponse>"""

        def h(req):
            seen["auth"] = req.headers.get("Authorization", "")
            return Response(200, xml.encode(), "text/xml")
        srv = HTTPServer("127.0.0.1", 0)
        srv.route("/", h)
        srv.start()
        out = discovery.ec2_sd({
            "endpoint": f"http://127.0.0.1:{srv.port}/",
            "region": "us-east-1", "port": 9100,
            "access_key": "AKID", "secret_key": "SECRET"})
        srv.stop()
        assert out == [("172.1.2.3:9100", {
            "__meta_ec2_instance_id": "i-123",
            "__meta_ec2_private_ip": "172.1.2.3",
            "__meta_ec2_instance_type": "t3.micro",
            "__meta_ec2_availability_zone": "us-east-1a",
            "__meta_ec2_instance_state": "running",
            "__meta_ec2_tag_Name": "api"})]
        assert seen["auth"].startswith("AWS4-HMAC-SHA256 Credential=AKID/")

    def test_sd_target_sync_relabel_and_removal(self, tmp_path):
        from victoriametrics_tpu.apps.vmagent import VMAgent
        cfg = {"scrape_configs": [{
            "job_name": "k",
            "static_configs": [{"targets": ["1.2.3.4:9100"]}],
            "relabel_configs": [
                {"source_labels": ["__address__"],
                 "target_label": "box"}],
        }]}
        a = VMAgent(cfg, [], str(tmp_path))
        assert len(a.targets) == 1
        t = list(a.targets.values())[0]
        assert t.labels == {"job": "k", "box": "1.2.3.4:9100",
                            "instance": "1.2.3.4:9100"}
        assert t.url == "http://1.2.3.4:9100/metrics"
        # config reload removes the target
        a.reload({"scrape_configs": []})
        assert a.targets == {}
        a.stop()

    def test_sd_error_keeps_last_good_targets(self):
        from victoriametrics_tpu.ingest import discovery
        calls = {"n": 0}

        def flaky(cfg):
            calls["n"] += 1
            if calls["n"] == 2:
                raise discovery.DiscoveryError("api down")
            return [("1.1.1.1:80", {"__meta_x": "y"})]
        old = discovery.PROVIDERS.get("consul_sd_configs")
        discovery.PROVIDERS["consul_sd_configs"] = flaky
        try:
            lg = {}
            sc = {"consul_sd_configs": [{"server": "x"}]}
            t1 = discovery.discover_targets(sc, lg)
            t2 = discovery.discover_targets(sc, lg)  # provider errors
            assert t1 == t2 == [("1.1.1.1:80", {"__meta_x": "y"})]
        finally:
            discovery.PROVIDERS["consul_sd_configs"] = old


class TestVMAlertReplayRestore:
    def test_replay_writes_historic_recordings(self, tmp_path, vmsingle):
        client, storage = vmsingle
        # seed a counter over a 30-min historic window
        rows = [({"__name__": "rc", "i": "1"}, T0 + j * 15_000, 150.0 * j)
                for j in range(121)]
        storage.add_rows(rows)
        from victoriametrics_tpu.apps.vmalert import (Datasource, Group,
                                                      RemoteWriter, replay)
        base = f"http://127.0.0.1:{storage_port(client)}"
        ds = Datasource(base)
        rw = RemoteWriter(base)
        g = Group({"name": "g", "interval": "5m", "rules": [
            {"record": "rc:rate5m", "expr": "rate(rc[5m])"}]}, ds, [], rw)
        n = replay([g], T0 + 600_000, T0 + 1_500_000)
        assert n == 4  # 15min span at 5m interval inclusive
        r = client.query_range("rc:rate5m", (T0 + 600_000) / 1e3,
                               (T0 + 1_500_000) / 1e3, 300)
        res = r["data"]["result"]
        assert len(res) == 1
        vals = {v for _, v in res[0]["values"]}
        assert "10" in vals  # 150/15s = 10/s

    def test_state_restore(self, tmp_path, vmsingle):
        client, storage = vmsingle
        import time as _t
        now = _t.time()
        active_at = now - 120  # alert has been pending for 2 minutes
        storage.add_rows([
            ({"__name__": "ALERTS_FOR_STATE", "alertname": "HighLoad",
              "sev": "warn"}, int((now - 30) * 1000), active_at),
            ({"__name__": "trigger_metric"}, int(now * 1000), 1.0),
        ])
        from victoriametrics_tpu.apps.vmalert import (AlertingRule,
                                                      Datasource, Group)
        base = f"http://127.0.0.1:{storage_port(client)}"
        ds = Datasource(base)
        g = Group({"name": "g", "rules": [
            {"alert": "HighLoad", "expr": "trigger_metric > 0",
             "for": "3m", "labels": {"sev": "warn"}}]}, ds, [], None)
        g.restore(ds)
        rule = g.rules[0]
        assert len(rule._active) == 1
        st = list(rule._active.values())[0]
        assert abs(st["activeAt"] - active_at) < 1.0
        # next eval: still pending (3m not yet reached), keeps old activeAt
        g.eval_once(now)
        st = list(rule._active.values())[0]
        assert st["state"] == "pending"
        assert abs(st["activeAt"] - active_at) < 1.0
        # a minute later the restored clock crosses `for` -> firing
        g.eval_once(now + 70)
        st = list(rule._active.values())[0]
        assert st["state"] == "firing"


def storage_port(client) -> int:
    return int(client.base.rsplit(":", 1)[1])


class TestS3Backup:
    def test_backup_restore_via_fake_s3(self, tmp_path, vmsingle):
        """A minimal in-process S3 server: PUT/GET/DELETE objects +
        ListObjectsV2, like the reference's custom-endpoint tests."""
        import urllib.parse
        from victoriametrics_tpu.httpapi.server import HTTPServer, Response
        objects: dict[str, bytes] = {}

        def handler(req):
            path = urllib.parse.unquote(req.path.lstrip("/"))
            if req.method == "PUT":
                objects[path] = req.body
                return Response(200, b"")
            if req.method == "DELETE":
                objects.pop(path, None)
                return Response(204, b"")
            if req.method == "GET" and req.arg("list-type") == "2":
                bucket = path.split("?")[0]
                prefix = req.arg("prefix", "")
                # real S3 keys exclude the bucket name
                items = "".join(
                    f"<Contents><Key>{k[len(bucket) + 1:]}</Key>"
                    f"<Size>{len(v)}</Size></Contents>"
                    for k, v in objects.items()
                    if k.startswith(bucket + "/" + prefix))
                xml = (f"<ListBucketResult>{items}"
                       f"<IsTruncated>false</IsTruncated></ListBucketResult>")
                return Response(200, xml.encode(), "application/xml")
            if req.method == "GET":
                if path in objects:
                    return Response(200, objects[path],
                                    "application/octet-stream")
                return Response(404, b"not found")
            return Response(400, b"")
        srv = HTTPServer("127.0.0.1", 0)
        srv.route("/", handler)
        srv.prefix_routes.append(("/", handler))
        srv.start()

        client, storage = vmsingle
        storage.add_rows([({"__name__": "s3m", "i": str(i)}, T0, float(i))
                          for i in range(30)])
        storage.force_flush()
        snap = storage.create_snapshot()
        snap_dir = os.path.join(storage.snapshots_dir(), snap)
        from victoriametrics_tpu.apps.vmbackup import (S3Remote, backup,
                                                       restore)
        remote = S3Remote("bkt", "backups/daily",
                          endpoint=f"http://127.0.0.1:{srv.port}",
                          access_key="AK", secret_key="SK")
        st = backup(snap_dir, remote)
        assert st["uploaded"] > 0
        st2 = backup(snap_dir, remote)  # incremental: nothing re-uploaded
        assert st2["uploaded"] == 0 and st2["skipped"] == st["uploaded"]
        dst = str(tmp_path / "restored-s3")
        restore(remote, dst)
        from victoriametrics_tpu.storage.storage import Storage
        from victoriametrics_tpu.storage.tag_filters import filters_from_dict
        s2 = Storage(dst)
        res = s2.search_series(filters_from_dict({"__name__": "s3m"}),
                               T0 - 1000, T0 + 1000)
        assert len(res) == 30
        s2.close()
        srv.stop()


class TestGcsBackup:
    def test_backup_restore_via_fake_gcs(self, tmp_path, vmsingle):
        """In-process GCS JSON API fake (fake-gcs-server analog): object
        list with pagination + media upload/download/delete."""
        import json as _json
        import urllib.parse
        from victoriametrics_tpu.httpapi.server import HTTPServer, Response
        objects: dict[str, bytes] = {}
        seen_auth = []

        def handler(req):
            seen_auth.append(req.headers.get("authorization", ""))
            path = req.path
            if req.method == "POST" and path.startswith("/upload/"):
                name = urllib.parse.unquote(req.arg("name"))
                objects[name] = req.body
                return Response(200, _json.dumps(
                    {"name": name, "size": str(len(req.body))}).encode())
            if req.method == "GET" and path == "/storage/v1/b/bkt/o":
                prefix = req.arg("prefix", "")
                keys = sorted(k for k in objects if k.startswith(prefix))
                # paginate 2 at a time to exercise pageToken
                start = int(req.arg("pageToken") or 0)
                page = keys[start:start + 2]
                resp = {"items": [{"name": k, "size": str(len(objects[k]))}
                                  for k in page]}
                if start + 2 < len(keys):
                    resp["nextPageToken"] = str(start + 2)
                return Response(200, _json.dumps(resp).encode())
            if path.startswith("/storage/v1/b/bkt/o/"):
                name = urllib.parse.unquote(
                    path[len("/storage/v1/b/bkt/o/"):])
                if req.method == "DELETE":
                    return (Response(204, b"") if objects.pop(name, None)
                            is not None else Response(404, b""))
                if req.method == "GET" and name in objects:
                    return Response(200, objects[name],
                                    "application/octet-stream")
                return Response(404, b"not found")
            return Response(400, b"bad request")
        srv = HTTPServer("127.0.0.1", 0)
        srv.route("/", handler)
        srv.prefix_routes.append(("/", handler))
        srv.start()

        client, storage = vmsingle
        storage.add_rows([({"__name__": "gm", "i": str(i)}, T0, float(i))
                          for i in range(20)])
        storage.force_flush()
        snap = storage.create_snapshot()
        snap_dir = os.path.join(storage.snapshots_dir(), snap)
        from victoriametrics_tpu.apps.vmbackup import (GcsRemote, backup,
                                                       open_remote, restore)
        remote = open_remote("gs://bkt/backups/g1",
                             endpoint=f"http://127.0.0.1:{srv.port}",
                             token="tok123")
        assert isinstance(remote, GcsRemote)
        st = backup(snap_dir, remote)
        assert st["uploaded"] > 0
        st2 = backup(snap_dir, remote)
        assert st2["uploaded"] == 0 and st2["skipped"] == st["uploaded"]
        assert any(a == "Bearer tok123" for a in seen_auth)
        dst = str(tmp_path / "restored-gcs")
        restore(remote, dst)
        from victoriametrics_tpu.storage.storage import Storage
        from victoriametrics_tpu.storage.tag_filters import filters_from_dict
        s2 = Storage(dst)
        res = s2.search_series(filters_from_dict({"__name__": "gm"}),
                               T0 - 1000, T0 + 1000)
        assert len(res) == 20
        s2.close()
        srv.stop()


class TestAzblobBackup:
    def test_backup_restore_via_fake_azurite(self, tmp_path, vmsingle):
        """In-process Azure Blob fake that VERIFIES SharedKey signatures
        (x-ms-date canonicalization + HMAC-SHA256 over the account key),
        plus container listing with marker pagination."""
        import base64
        import hashlib
        import hmac as _hmac
        import urllib.parse
        from victoriametrics_tpu.httpapi.server import HTTPServer, Response
        account, acct_key = "devacct", base64.b64encode(b"secret-key")
        objects: dict[str, bytes] = {}
        bad_sigs = []

        def check_sig(req, query):
            auth = req.headers.get("authorization", "")
            if not auth.startswith(f"SharedKey {account}:"):
                bad_sigs.append(("missing", auth))
                return
            xms = {k: v for k, v in req.headers.items()
                   if k.lower().startswith("x-ms-")}
            canon_h = "".join(f"{k.lower()}:{v}\n"
                              for k, v in sorted(xms.items()))
            canon_r = f"/{account}{req.path}"
            if query:
                params = urllib.parse.parse_qs(query,
                                               keep_blank_values=True)
                for k in sorted(params):
                    canon_r += f"\n{k.lower()}:{','.join(params[k])}"
            cl = str(len(req.body)) if req.body else ""
            ct = req.headers.get("content-type", "")
            to_sign = (f"{req.method}\n\n\n{cl}\n\n{ct}\n\n\n\n\n\n\n"
                       f"{canon_h}{canon_r}")
            want = base64.b64encode(_hmac.new(
                base64.b64decode(acct_key), to_sign.encode(),
                hashlib.sha256).digest()).decode()
            if auth != f"SharedKey {account}:{want}":
                bad_sigs.append((to_sign, auth))

        def handler(req):
            query = urllib.parse.urlparse(req.handler.path).query
            check_sig(req, query)
            path = urllib.parse.unquote(req.path.lstrip("/"))
            if req.method == "GET" and req.arg("comp") == "list":
                prefix = req.arg("prefix", "")
                keys = sorted(k for k in objects if k.startswith(prefix))
                start = int(req.arg("marker") or 0)
                page = keys[start:start + 2]
                blobs = "".join(
                    f"<Blob><Name>{k}</Name><Properties>"
                    f"<Content-Length>{len(objects[k])}</Content-Length>"
                    f"</Properties></Blob>" for k in page)
                nm = (f"<NextMarker>{start + 2}</NextMarker>"
                      if start + 2 < len(keys) else "<NextMarker/>")
                xml = (f"<EnumerationResults><Blobs>{blobs}</Blobs>{nm}"
                       f"</EnumerationResults>")
                return Response(200, xml.encode(), "application/xml")
            key = path.split("/", 1)[1] if "/" in path else ""
            if req.method == "PUT":
                objects[key] = req.body
                return Response(201, b"")
            if req.method == "DELETE":
                return (Response(202, b"") if objects.pop(key, None)
                        is not None else Response(404, b""))
            if req.method == "GET":
                if key in objects:
                    return Response(200, objects[key],
                                    "application/octet-stream")
                return Response(404, b"not found")
            return Response(400, b"")
        srv = HTTPServer("127.0.0.1", 0)
        srv.route("/", handler)
        srv.prefix_routes.append(("/", handler))
        srv.start()

        client, storage = vmsingle
        storage.add_rows([({"__name__": "azm", "i": str(i)}, T0, float(i))
                          for i in range(15)])
        storage.force_flush()
        snap = storage.create_snapshot()
        snap_dir = os.path.join(storage.snapshots_dir(), snap)
        from victoriametrics_tpu.apps.vmbackup import (AzblobRemote, backup,
                                                       open_remote, restore)
        remote = open_remote("azblob://cont/backups/a1",
                             endpoint=f"http://127.0.0.1:{srv.port}",
                             account=account, key=acct_key.decode())
        assert isinstance(remote, AzblobRemote)
        st = backup(snap_dir, remote)
        assert st["uploaded"] > 0
        assert not bad_sigs, bad_sigs[0]
        st2 = backup(snap_dir, remote)
        assert st2["uploaded"] == 0 and st2["skipped"] == st["uploaded"]
        dst = str(tmp_path / "restored-az")
        restore(remote, dst)
        from victoriametrics_tpu.storage.storage import Storage
        from victoriametrics_tpu.storage.tag_filters import filters_from_dict
        s2 = Storage(dst)
        res = s2.search_series(filters_from_dict({"__name__": "azm"}),
                               T0 - 1000, T0 + 1000)
        assert len(res) == 15
        s2.close()
        srv.stop()


class TestJWT:
    def _hs_token(self, secret, claims):
        import base64, hashlib, hmac, json as _json
        enc = lambda b: base64.urlsafe_b64encode(b).rstrip(b"=").decode()
        h = enc(_json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
        p = enc(_json.dumps(claims).encode())
        sig = hmac.new(secret.encode(), f"{h}.{p}".encode(),
                       hashlib.sha256).digest()
        return f"{h}.{p}.{enc(sig)}"

    def test_hs256_verify(self):
        import time as _t
        from victoriametrics_tpu.utils.jwt import JWTError, verify
        tok = self._hs_token("s3cret", {"sub": "u1",
                                        "exp": _t.time() + 60})
        assert verify(tok, secrets=["wrong", "s3cret"])["sub"] == "u1"
        import pytest as _pt
        with _pt.raises(JWTError, match="signature"):
            verify(tok, secrets=["nope"])
        expired = self._hs_token("s3cret", {"exp": _t.time() - 10})
        with _pt.raises(JWTError, match="expired"):
            verify(expired, secrets=["s3cret"])

    def test_vmauth_jwt_user(self):
        from victoriametrics_tpu.apps.vmauth import AuthConfig
        cfg = {"users": [{
            "name": "jwty", "url_prefix": "http://b1",
            "jwt_secrets": ["topsecret"],
            "jwt_required_claims": {"team": "dev"}}]}
        auth = AuthConfig(cfg)
        good = self._hs_token("topsecret", {"team": "dev"})
        bad_claim = self._hs_token("topsecret", {"team": "ops"})
        bad_sig = self._hs_token("other", {"team": "dev"})
        assert auth.find_user(
            {"Authorization": f"Bearer {good}"}).name == "jwty"
        assert auth.find_user(
            {"Authorization": f"Bearer {bad_claim}"}) is None
        assert auth.find_user(
            {"Authorization": f"Bearer {bad_sig}"}) is None


class TestRemoteRead:
    def test_vmctl_remote_read_migration(self, tmp_path, vmsingle):
        client, storage = vmsingle
        storage.add_rows([({"__name__": "rrm", "i": str(i)},
                           T0 + j * 15_000, float(i * 10 + j))
                          for i in range(5) for j in range(20)])
        # destination vmsingle
        from victoriametrics_tpu.apps.vmsingle import build, parse_flags
        args = parse_flags([f"-storageDataPath={tmp_path}/rrdst",
                            "-httpListenAddr=127.0.0.1:0"])
        storage2, srv2, _ = build(args)
        srv2.start()
        try:
            from victoriametrics_tpu.apps.vmctl import remote_read
            src = client.base
            dst = f"http://127.0.0.1:{srv2.port}"
            n = remote_read(src, dst, '{__name__="rrm"}',
                            T0, T0 + 20 * 15_000)
            assert n == 100
            c2 = Client(srv2.port)
            r = c2.query("count(rrm)", (T0 + 19 * 15_000) / 1e3)
            assert r["data"]["result"][0]["value"][1] == "5"
        finally:
            srv2.stop()
            storage2.close()
