"""Ring-buffer rollup result cache (O(new samples) steady-state serving):
in-place tail merges must be indistinguishable — bit for bit — from the
full-rebuild oracle (VM_RESULT_CACHE_RING=0) and from a cold nocache
evaluation, across rolling refreshes, series churn, the volatile-tail
clip and backfill resets; byte-bounded LRU eviction; and the
serve-priority merge gate."""

import hashlib
import os
import threading
import time

import numpy as np
import pytest

from victoriametrics_tpu.httpapi.prometheus_api import PrometheusAPI
from victoriametrics_tpu.query import rollup_result_cache as rrc
from victoriametrics_tpu.query.exec import exec_query
from victoriametrics_tpu.query.types import EvalConfig
from victoriametrics_tpu.storage.storage import Storage
from victoriametrics_tpu.utils import metrics as metricslib

STEP = 60_000
SCRAPE = 15_000
N0 = 400          # initial scrapes per series
NS = 12           # series
DUR = 40 * STEP   # query window


def _sha(rows) -> str:
    h = hashlib.sha256()
    for ts in sorted(rows, key=lambda t: t.metric_name.marshal()):
        h.update(ts.metric_name.marshal())
        h.update(np.ascontiguousarray(ts.values).tobytes())
    return h.hexdigest()


def _mk_store(tmp_path, name="s") -> tuple[Storage, int]:
    """Store with live-anchored counters (fresh scrapes land inside the
    OFFSET_MS volatile window, as in production)."""
    s = Storage(str(tmp_path / name))
    now = int(time.time() * 1000)
    t0 = (now - (N0 - 1) * SCRAPE) // STEP * STEP
    s.add_rows([({"__name__": "ringm", "i": str(i), "g": f"g{i % 3}"},
                 t0 + j * SCRAPE, float(j + i))
                for i in range(NS) for j in range(N0)])
    s.force_flush()
    end0 = t0 + ((N0 - 1) * SCRAPE // STEP + 1) * STEP
    return s, end0


def _ingest(s, end_ms, lo=0, hi=NS, bump=0.0):
    s.add_rows([({"__name__": "ringm", "i": str(i), "g": f"g{i % 3}"},
                 end_ms - STEP + (k + 1) * SCRAPE,
                 float(2000 + bump + i + k))
                for i in range(lo, hi) for k in range(4)])


def _cold(s, q, start, end):
    return exec_query(EvalConfig(start=start, end=end, step=STEP,
                                 storage=s, disable_cache=True), q)


@pytest.fixture(autouse=True)
def _fresh_cache():
    rrc.GLOBAL.reset()
    yield
    rrc.GLOBAL.reset()
    os.environ.pop("VM_RESULT_CACHE_RING", None)


QUERIES = ["sum by (g)(rate(ringm[5m]))", "rate(ringm[5m])"]


class TestRingServedEqualsCold:
    @pytest.mark.parametrize("q", QUERIES)
    def test_rolling_refreshes(self, tmp_path, q):
        s, end = _mk_store(tmp_path)
        api = PrometheusAPI(s)
        inp0 = metricslib.REGISTRY.counter(
            "vm_rollup_cache_inplace_total").get()
        start = end - DUR
        api._exec_range_cached(EvalConfig(start=start, end=end, step=STEP,
                                          storage=s), q,
                               int(time.time() * 1000))
        for r in range(4):
            end += STEP
            start = end - DUR
            _ingest(s, end, bump=r)
            served = api._exec_range_cached(
                EvalConfig(start=start, end=end, step=STEP, storage=s), q,
                int(time.time() * 1000))
            assert _sha(served) == _sha(_cold(s, q, start, end)), \
                f"refresh {r} diverged from cold"
        assert metricslib.REGISTRY.counter(
            "vm_rollup_cache_inplace_total").get() > inp0
        s.close()

    def test_new_series_appears_and_vanishes(self, tmp_path):
        q = QUERIES[0]
        s, end = _mk_store(tmp_path)
        api = PrometheusAPI(s)
        start = end - DUR
        api._exec_range_cached(EvalConfig(start=start, end=end, step=STEP,
                                          storage=s), q,
                               int(time.time() * 1000))
        # series i=0 vanishes, i=NS..NS+3 appear mid-window
        for r in range(3):
            end += STEP
            start = end - DUR
            _ingest(s, end, lo=1, hi=NS + 4, bump=10 * r)
            served = api._exec_range_cached(
                EvalConfig(start=start, end=end, step=STEP, storage=s), q,
                int(time.time() * 1000))
            assert _sha(served) == _sha(_cold(s, q, start, end))
        s.close()

    def test_backfill_resets_and_recovers(self, tmp_path):
        q = QUERIES[0]
        s, end = _mk_store(tmp_path)
        api = PrometheusAPI(s)
        start = end - DUR
        api._exec_range_cached(EvalConfig(start=start, end=end, step=STEP,
                                          storage=s), q,
                               int(time.time() * 1000))
        # backfill far behind the OFFSET window -> cache reset
        s.add_rows([({"__name__": "ringm", "i": "0", "g": "g0"},
                     end - 3 * DUR, 1.0)])
        assert rrc.GLOBAL.stats()["entries"] == 0
        for r in range(2):
            end += STEP
            start = end - DUR
            _ingest(s, end, bump=50 + r)
            served = api._exec_range_cached(
                EvalConfig(start=start, end=end, step=STEP, storage=s), q,
                int(time.time() * 1000))
            assert _sha(served) == _sha(_cold(s, q, start, end))
        s.close()

    @pytest.mark.parametrize("q", QUERIES)
    def test_ring_on_off_identical_rows(self, tmp_path, q):
        """Acceptance: VM_RESULT_CACHE_RING=0 and =1 produce identical
        rows for the same refresh sequence."""
        shas = {}
        for ring in ("0", "1"):
            os.environ["VM_RESULT_CACHE_RING"] = ring
            rrc.GLOBAL.reset()
            s, end = _mk_store(tmp_path, name=f"ring{ring}-{hash(q) % 97}")
            api = PrometheusAPI(s)
            start = end - DUR
            api._exec_range_cached(
                EvalConfig(start=start, end=end, step=STEP, storage=s), q,
                int(time.time() * 1000))
            seq = []
            for r in range(3):
                end += STEP
                start = end - DUR
                _ingest(s, end, bump=r)  # same data both rounds
                served = api._exec_range_cached(
                    EvalConfig(start=start, end=end, step=STEP,
                               storage=s), q, int(time.time() * 1000))
                seq.append(_sha(served))
                assert _sha(served) == _sha(_cold(s, q, start, end))
            shas[ring] = seq
            s.close()
        assert shas["0"] == shas["1"]


class TestRingEntryMechanics:
    def test_views_stay_valid_across_compaction(self, tmp_path):
        """An in-place merge that compacts into a fresh buffer must not
        corrupt rows returned by the PREVIOUS merge (old buffer intact)."""
        q = QUERIES[0]
        s, end = _mk_store(tmp_path)
        api = PrometheusAPI(s)
        start = end - DUR
        api._exec_range_cached(EvalConfig(start=start, end=end, step=STEP,
                                          storage=s), q,
                               int(time.time() * 1000))
        prev = None
        prev_copy = None
        # enough refreshes to exhaust COL_HEADROOM and force a compaction
        for r in range(rrc.COL_HEADROOM + 4):
            end += STEP
            start = end - DUR
            _ingest(s, end, bump=r)
            served = api._exec_range_cached(
                EvalConfig(start=start, end=end, step=STEP, storage=s), q,
                int(time.time() * 1000))
            if prev is not None:
                for ts, want in zip(prev, prev_copy):
                    np.testing.assert_array_equal(ts.values, want)
            prev = served
            prev_copy = [ts.values.copy() for ts in served]
        s.close()

    def test_held_rows_survive_next_merge_with_changed_tail(self, tmp_path):
        """Rows handed out by one merge must stay stable while a LATER
        merge of the same key rewrites the volatile tail (a concurrent
        viewer of the same dashboard still serializing the previous
        response).  A late sample inside the OFFSET window (no cache
        reset) changes the recomputed tail values, so a write-through
        merge would visibly mutate the held rows."""
        q = QUERIES[0]
        s, end = _mk_store(tmp_path)
        api = PrometheusAPI(s)
        start = end - DUR
        api._exec_range_cached(EvalConfig(start=start, end=end, step=STEP,
                                          storage=s), q,
                               int(time.time() * 1000))
        end += STEP
        _ingest(s, end)
        held = api._exec_range_cached(
            EvalConfig(start=end - DUR, end=end, step=STEP, storage=s), q,
            int(time.time() * 1000))
        held_copy = [ts.values.copy() for ts in held]
        # late sample in the volatile tail: newer than the entry's
        # coverage (no backfill reset) but inside held's served window,
        # so the next refresh recomputes those columns to NEW values
        s.add_rows([({"__name__": "ringm", "i": "0", "g": "g0"},
                     end - 2 * STEP + 7_000, 99_999.0)])
        end += STEP
        _ingest(s, end, bump=3)
        served = api._exec_range_cached(
            EvalConfig(start=end - DUR, end=end, step=STEP, storage=s), q,
            int(time.time() * 1000))
        for ts, want in zip(held, held_copy):
            np.testing.assert_array_equal(ts.values, want)
        assert _sha(served) == _sha(_cold(s, q, end - DUR, end))
        s.close()

    def test_nonlive_window_refresh_stays_o_suffix(self, tmp_path):
        """A dashboard whose window ends BEFORE now-OFFSET gets a
        single-column tail per refresh, which the HTTP executor widens to
        a 2-point sub-eval.  That sub must not write eval-level cache
        entries under its short window (no_eval_cache, same guard as the
        eval-level suffix subs): a clobbered inner entry forces the next
        refresh into a full-window recompute."""
        q = QUERIES[0]
        s, end = _mk_store(tmp_path)
        api = PrometheusAPI(s)
        end -= 20 * STEP          # well behind now - OFFSET_MS: no tail trim
        dur = 60 * STEP           # suffix fetch (window+lookback ~11min)
        start = end - dur         # stays well under 30% of this window
        cold_ec = EvalConfig(start=start, end=end, step=STEP, storage=s,
                             disable_cache=True)
        exec_query(cold_ec, q)
        cold_samples = cold_ec.samples_scanned
        assert cold_samples > 0
        api._exec_range_cached(EvalConfig(start=start, end=end, step=STEP,
                                          storage=s), q,
                               int(time.time() * 1000))
        for r in range(3):
            end += STEP
            start = end - dur
            ec = EvalConfig(start=start, end=end, step=STEP, storage=s)
            served = api._exec_range_cached(ec, q, int(time.time() * 1000))
            assert _sha(served) == _sha(_cold(s, q, start, end))
            assert ec.samples_scanned < 0.3 * cold_samples
            # the clobber is invisible behind the HTTP-level entry: probe
            # the shared eval-level (fused) entry with a direct eval — a
            # sub that replaced it with its 2-column window forces this
            # into a full-window recompute
            ev = EvalConfig(start=start, end=end, step=STEP, storage=s)
            exec_query(ev, q)
            assert ev.samples_scanned < 0.3 * cold_samples, (
                f"refresh {r}: eval-level query scanned "
                f"{ev.samples_scanned} of a {cold_samples}-sample window:"
                f" the widened HTTP tail sub clobbered the shared "
                f"eval-level cache entry")
        s.close()

    def test_full_hit_after_noop_put_is_filtered_and_sorted(self, tmp_path):
        """An in-place merge keeps append-ordered rows in the entry and
        stamps the following put() into a no-op, skipping the caller's
        filter+sort.  A later full hit of the same window must re-apply
        both, or its row order diverges from the partial-hit responses
        and from the VM_RESULT_CACHE_RING=0 oracle."""
        q = "rate(ringm[5m])"
        s, end0 = _mk_store(tmp_path)
        # a series that exists ONLY just after the initial window end:
        # rolling over it appends its row at the END of the ring entry,
        # while its label (i="!!" < "0") sorts FIRST
        s.add_rows([({"__name__": "ringm", "i": "!!", "g": "g0"},
                     end0 - 19 * STEP + k * SCRAPE, float(k))
                    for k in range(8)])
        s.force_flush()
        api = PrometheusAPI(s)
        end = end0 - 20 * STEP    # non-live: no volatile-tail trim
        dur = 30 * STEP
        start = end - dur
        api._exec_range_cached(EvalConfig(start=start, end=end, step=STEP,
                                          storage=s), q,
                               int(time.time() * 1000))
        for _ in range(3):        # roll over the "!!" series' samples
            end += STEP
            start = end - dur
            api._exec_range_cached(EvalConfig(start=start, end=end,
                                              step=STEP, storage=s), q,
                                   int(time.time() * 1000))
        # same window again: full hit served straight from the entry
        full = api._exec_range_cached(
            EvalConfig(start=start, end=end, step=STEP, storage=s), q,
            int(time.time() * 1000))
        raws = [ts.raw for ts in full]
        assert any(b'"!!"' in r or b"!!" in r for r in raws)
        assert raws == sorted(raws), \
            "full hit returned entry append order, not the sorted order " \
            "partial hits serve"
        assert not any(np.isnan(ts.values).all() for ts in full)
        assert _sha(full) == _sha(_cold(s, q, start, end))
        s.close()

    def test_merged_rows_are_read_only_views(self, tmp_path):
        q = QUERIES[0]
        s, end = _mk_store(tmp_path)
        api = PrometheusAPI(s)
        start = end - DUR
        api._exec_range_cached(EvalConfig(start=start, end=end, step=STEP,
                                          storage=s), q,
                               int(time.time() * 1000))
        end += STEP
        _ingest(s, end)
        served = api._exec_range_cached(
            EvalConfig(start=end - DUR, end=end, step=STEP, storage=s), q,
            int(time.time() * 1000))
        assert served and not served[0].values.flags.writeable
        with pytest.raises((ValueError, RuntimeError)):
            served[0].values[0] = 1.0
        s.close()

    def test_read_only_views_survive_group_join_dup_merge(self):
        """Regression: group_left with duplicate joined names merges the
        'one' side IN PLACE (binary_op mergeNonOverlappingTimeseries); the
        merge destination must own its values, because ring-cache partial
        hits hand the eval read-only views (and copy_shallow_labels shares
        the underlying array)."""
        from victoriametrics_tpu.query.binary_op import eval_binary_op
        from victoriametrics_tpu.query.metricsql.ast import ModifierExpr
        from victoriametrics_tpu.query.types import Timeseries
        from victoriametrics_tpu.storage.metric_name import MetricName

        def ro(vals):
            a = np.array(vals)
            a.setflags(write=False)
            return a

        many = [Timeseries(MetricName(b"m", [(b"instance", b"a")]),
                           ro([1.0, 2.0, 3.0, 4.0]))]
        # same on(instance) signature, join tags leave the joined names
        # identical -> duplicate path; complementary NaN masks -> merge ok
        one = [Timeseries(MetricName(b"o", [(b"instance", b"a"),
                                            (b"le", b"x")]),
                          ro([1.0, np.nan, np.nan, np.nan])),
               Timeseries(MetricName(b"o", [(b"instance", b"a"),
                                            (b"le", b"y")]),
                          ro([np.nan, 2.0, 2.0, 2.0]))]
        out = eval_binary_op("*", many, one, False,
                             ModifierExpr(op="on", args=["instance"]),
                             ModifierExpr(op="group_left"), False)
        assert len(out) == 1
        np.testing.assert_array_equal(out[0].values, [1.0, 4.0, 6.0, 8.0])
        # inputs stay untouched (the merge wrote into an owned copy)
        np.testing.assert_array_equal(one[0].values,
                                      [1.0, np.nan, np.nan, np.nan])

    def test_partial_results_never_committed_inplace(self):
        """A partial suffix (cluster node down) must not mutate the live
        entry: merge takes the pure rebuild path and the entry keeps its
        pre-merge coverage (the never-cache-partial contract)."""
        from victoriametrics_tpu.query.types import new_series
        c = rrc.RollupResultCache(max_entries=8)

        class _St:
            cache_token = 991201

        now = int(time.time() * 1000)
        start = (now - 3600_000) // STEP * STEP
        end = start + 10 * STEP

        def mk_rows(n):
            r = [new_series(np.arange(n, dtype=np.float64),
                            labels=[(b"i", b"0")])]
            for ts in r:
                ts.raw = ts.metric_name.marshal()
            return r

        ec = EvalConfig(start=start, end=end, step=STEP, storage=_St())
        c.put(ec, "q", mk_rows(ec.n_points), now)
        ec2 = EvalConfig(start=start + STEP, end=end + STEP, step=STEP,
                         storage=_St())
        hit, new_start = c.get(ec2, "q", now)
        assert hit is not None and new_start == end + STEP
        gen0 = hit.entry.gen
        c_end0 = hit.entry.c_end
        ec2._partial[0] = True  # the suffix fetch was partial
        fresh = mk_rows(1)
        rows = c.merge(hit, fresh, ec2, new_start, now_ms=now)
        assert len(rows) == 1  # still served
        assert hit.entry.gen == gen0 and hit.entry.c_end == c_end0, \
            "partial suffix was committed into the live entry"

    def test_compaction_prunes_vanished_series_rows(self, tmp_path):
        """Series churn must not grow a hot entry's rows without bound:
        rows whose remaining prefix is all-NaN drop at compaction."""
        q = QUERIES[1]  # per-series rows: rate(ringm[5m])
        s, end = _mk_store(tmp_path)
        api = PrometheusAPI(s)
        start = end - DUR
        api._exec_range_cached(EvalConfig(start=start, end=end, step=STEP,
                                          storage=s), q,
                               int(time.time() * 1000))
        # each round retires one series id and mints a new one: constant
        # LIVE cardinality (NS), ever-churning identity
        rounds = 2 * (rrc.COL_HEADROOM + DUR // STEP) + 8
        for r in range(rounds):
            end += STEP
            start = end - DUR
            _ingest(s, end, lo=r + 1, hi=r + 1 + NS, bump=r)
            api._exec_range_cached(
                EvalConfig(start=start, end=end, step=STEP, storage=s), q,
                int(time.time() * 1000))
        key = (s.cache_token, (0, 0), q, STEP)
        with rrc.GLOBAL._lock:
            e = rrc.GLOBAL._cache.get(key)
        assert e is not None
        # without pruning the entry would hold every identity ever seen
        # (NS + rounds rows); with compaction-time pruning it is bounded
        # by live series + the window depth + one headroom's worth of
        # churn since the last compaction
        assert e.n_rows < NS + DUR // STEP + rrc.COL_HEADROOM + 16, \
            f"{e.n_rows} rows cached for {NS} live series"
        assert e.n_rows < NS + rounds  # sanity: strictly better than none
        s.close()

    def test_byte_bound_evicts_lru(self):
        c = rrc.RollupResultCache(max_entries=100, max_bytes=1)

        class _St:
            cache_token = 991199

        now = int(time.time() * 1000)
        start = (now - 3600_000) // STEP * STEP
        end = start + 10 * STEP
        from victoriametrics_tpu.query.types import new_series
        for i in range(5):
            ec = EvalConfig(start=start, end=end, step=STEP, storage=_St())
            rows = [new_series(np.arange(ec.n_points, dtype=np.float64),
                               labels=[(b"i", str(i).encode())])]
            c.put(ec, f"q{i}", rows, now)
        # every entry is over the 1-byte budget: only the MRU one survives
        assert c.entry_count() == 1
        assert c.size_bytes() > 0
        # the limit is exported
        assert c.max_bytes == 1

    def test_put_identity_skip_counts_inplace(self, tmp_path):
        """Repeated puts of an unchanged series set reuse the entry's
        MetricName list (satellite: no per-refresh identity rebuild)."""
        c = rrc.RollupResultCache(max_entries=8)

        class _St:
            cache_token = 991200

        from victoriametrics_tpu.query.types import new_series
        now = int(time.time() * 1000)
        start = (now - 3600_000) // STEP * STEP
        end = start + 10 * STEP
        ec = EvalConfig(start=start, end=end, step=STEP, storage=_St())
        rows = [new_series(np.arange(ec.n_points, dtype=np.float64),
                           labels=[(b"i", b"0")])]
        for ts in rows:
            ts.raw = ts.metric_name.marshal()
        r0 = metricslib.REGISTRY.counter(
            "vm_rollup_cache_put_identity_reused_total").get()
        c.put(ec, "q", rows, now)
        c.put(ec, "q", rows, now)
        assert metricslib.REGISTRY.counter(
            "vm_rollup_cache_put_identity_reused_total").get() > r0


@pytest.mark.race
class TestRingRace:
    def test_concurrent_refreshes_ingest_and_reset(self, tmp_path):
        """Concurrent refreshes, live ingest and a mid-flight backfill
        reset over ONE cache entry: every served result must equal a cold
        eval of its own window (run under VMT_RACETRACE=1 via
        tools/race.sh for the sanitizer pass)."""
        q = QUERIES[0]
        s, end0 = _mk_store(tmp_path)
        api = PrometheusAPI(s)
        start = end0 - DUR
        api._exec_range_cached(EvalConfig(start=start, end=end0, step=STEP,
                                          storage=s), q,
                               int(time.time() * 1000))
        errors: list = []
        compared = [0]
        stop = threading.Event()

        def refresher():
            end = end0
            try:
                for r in range(6):
                    end += STEP
                    st = end - DUR
                    v0 = s.data_version
                    served = api._exec_range_cached(
                        EvalConfig(start=st, end=end, step=STEP,
                                   storage=s), q, int(time.time() * 1000))
                    cold = _cold(s, q, st, end)
                    if s.data_version != v0:
                        continue  # ingest landed between the two evals:
                        #           served/cold saw different data
                    compared[0] += 1
                    if _sha(served) != _sha(cold):
                        errors.append(f"refresh {r} diverged")
            except Exception as e:  # pragma: no cover - failure capture
                errors.append(repr(e))

        def ingester():
            end = end0
            try:
                for r in range(6):
                    end += STEP
                    _ingest(s, end, bump=r)
                    if r == 3:
                        # backfill: resets the cache mid-stream
                        s.add_rows([({"__name__": "ringm", "i": "0",
                                      "g": "g0"}, end0 - 3 * DUR, 1.0)])
                    time.sleep(0.005)
            except Exception as e:  # pragma: no cover - failure capture
                errors.append(repr(e))
            finally:
                stop.set()

        threads = [threading.Thread(target=refresher, daemon=True)
                   for _ in range(2)] + \
                  [threading.Thread(target=ingester, daemon=True)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        assert compared[0] > 0  # at least some served==cold pairs raced-free
        s.close()


class TestMergeGateServePriority:
    @staticmethod
    def _hold_serving(duration_s: float):
        """Hold a serving section on a SEPARATE thread (a thread inside
        its own serving section is exempt from the yield by design)."""
        from victoriametrics_tpu.utils import workpool
        started = threading.Event()

        def hold():
            with workpool.serving():
                started.set()
                time.sleep(duration_s)

        t = threading.Thread(target=hold, daemon=True)
        t.start()
        started.wait(5)
        return t

    def test_merge_defers_to_serving(self, monkeypatch):
        from victoriametrics_tpu.utils import workpool
        monkeypatch.setenv("VM_MERGE_YIELD_MS", "100")
        gate = workpool.MergeGate(limit=2)
        y0 = gate.yields
        holder = self._hold_serving(5.0)
        t0 = time.perf_counter()
        with gate:
            waited = time.perf_counter() - t0
        # yielded (counted) and resumed within the bounded budget
        assert gate.yields == y0 + 1
        assert 0.08 <= waited < 5.0
        holder.join(timeout=10)
        # no serving in flight: no yield
        t0 = time.perf_counter()
        with gate:
            pass
        assert time.perf_counter() - t0 < 0.08
        assert gate.yields == y0 + 1

    def test_merge_resumes_when_serving_drains(self, monkeypatch):
        from victoriametrics_tpu.utils import workpool
        monkeypatch.setenv("VM_MERGE_YIELD_MS", "5000")
        gate = workpool.MergeGate(limit=2)
        self._hold_serving(0.05)
        t0 = time.perf_counter()
        with gate:
            waited = time.perf_counter() - t0
        # resumed as soon as serving drained, far below the 5s budget
        assert waited < 2.0

    def test_no_yield_on_serving_or_pool_threads(self, monkeypatch):
        """Priority-inversion guard: a thread inside its own serving
        section, or a shared-POOL worker (holding a slot the serve's
        fetch tasks queue behind), must never sleep in the yield."""
        from victoriametrics_tpu.utils import workpool
        monkeypatch.setenv("VM_MERGE_YIELD_MS", "4000")
        gate = workpool.MergeGate(limit=2)
        holder = self._hold_serving(2.5)
        # self-serving thread: no deferral despite serving_busy()
        with workpool.serving():
            t0 = time.perf_counter()
            with gate:
                pass
            assert time.perf_counter() - t0 < 0.5
        # pool worker: flush-style task entering the gate must not stall.
        # submit + sleep so a REAL worker picks the task up (a single-item
        # run() executes inline on this thread, which isn't a worker)
        pool = workpool.WorkPool(workers=2)

        def merge_task():
            assert getattr(workpool._yield_tls, "pool_worker", False)
            t0 = time.perf_counter()
            with gate:
                return time.perf_counter() - t0

        fut = pool.submit(merge_task)
        time.sleep(0.2)
        waited = fut.result()
        assert waited < 0.5
        pool.shutdown()
        holder.join(timeout=10)
