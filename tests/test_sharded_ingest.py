"""Sharded ingestion pipeline (VM_INGEST_SHARDS): the acceptance
property — byte-identical data parts and identical data_version /
append-log observables between the striped parallel write path and the
sequential one — plus the two-generation cache rotation semantics, the
merge-concurrency gate, and the flusher-thread lifecycle.

Metric ids are time-seeded (MetricIDGenerator), so equality harnesses
pin the generator before ingesting; everything else is the production
code path.
"""

import hashlib
import os
import threading
import time

import numpy as np
import pytest

from victoriametrics_tpu.storage import partition as partition_mod
from victoriametrics_tpu.utils import metrics as metricslib
from victoriametrics_tpu.utils import workpool
from victoriametrics_tpu.utils.workingset import WorkingSetCache

try:
    from victoriametrics_tpu import native
    from victoriametrics_tpu.storage.storage import Storage
    from victoriametrics_tpu.storage.tag_filters import filters_from_dict
    _HAVE_STORAGE = True
except ImportError:  # optional deps (zstandard) missing
    _HAVE_STORAGE = False

needs_storage = pytest.mark.skipif(not _HAVE_STORAGE,
                                   reason="storage deps unavailable")
# canonical native gate (conftest skips the marked tests when the codec
# library is unavailable)
needs_native = pytest.mark.requires_native

T0 = 1_753_700_000_000  # 2025-07-28
DAY = 86_400_000


def _hash_tree(root) -> dict:
    """relpath -> sha256 for every file under root."""
    out = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for fn in sorted(filenames):
            full = os.path.join(dirpath, fn)
            with open(full, "rb") as f:
                out[os.path.relpath(full, root)] = \
                    hashlib.sha256(f.read()).hexdigest()
    return out


def _observables(s) -> tuple:
    return (s.data_version, list(s._append_log), s.rows_added,
            s.new_series_created)


def _mk_store(path, shards, monkeypatch, **kw) -> "Storage":
    monkeypatch.setenv("VM_INGEST_SHARDS", str(shards))
    monkeypatch.setenv("VM_SEARCH_WORKERS", "4" if shards > 1 else "1")
    s = Storage(str(path), **kw)
    s._mid_gen._next = 1_000_000  # deterministic ids across runs
    return s


def _legacy_rows():
    """dict labels + raw byte keys + a malformed key + day rollovers."""
    rows = []
    for i in range(40):
        rows.append(({"__name__": "leg", "i": str(i)},
                     T0 + i * 1000, float(i)))
    rows.append((b"bad{{{", T0, 9.0))            # malformed: dropped
    for i in range(20):
        rows.append((b'raw{i="%d"}' % i, T0 + i * 1000, float(i)))
    for i in range(40):                          # day rollover, fast path
        rows.append(({"__name__": "leg", "i": str(i)},
                     T0 + DAY + i * 1000, float(i + 1)))
    return rows


def _columnar_batches():
    keys = [f'cm{{i="{i}"}}'.encode() for i in range(32)]
    keybuf = b"".join(keys)
    klens = np.fromiter((len(k) for k in keys), np.int64, len(keys))
    koffs = np.concatenate([[0], np.cumsum(klens)[:-1]])
    out = []
    for step in range(3):
        k = 60
        ts = (T0 + (step * k + np.arange(k, dtype=np.int64))[None, :]
              * 15_000)
        ts = np.broadcast_to(ts, (len(keys), k)).reshape(-1).copy()
        if step == 2:
            ts = ts + DAY  # rollover batch
        vals = (ts % 10**9).astype(np.float64)
        out.append((keybuf, np.repeat(koffs, k), np.repeat(klens, k),
                    ts, vals))
    return out


# -- parallel vs sequential byte equality ------------------------------------

@needs_storage
class TestShardedEquality:
    def _finish(self, s):
        s.force_flush()
        obs = _observables(s)
        data = os.path.join(s.path, "data")
        s.close()
        return _hash_tree(data), obs

    def test_legacy_rows_byte_identical(self, tmp_path, monkeypatch):
        """add_rows with dict/bytes/malformed/day-rollover rows: the
        striped path's parts equal the sequential path's byte for byte
        (the async pending spill is forced via a tiny row cap)."""
        monkeypatch.setattr(partition_mod, "MAX_PENDING_ROWS", 64)
        results = []
        for shards, sub in ((1, "seq"), (4, "par")):
            s = _mk_store(tmp_path / sub, shards, monkeypatch)
            try:
                s.add_rows(_legacy_rows())
                s.add_rows(_legacy_rows())  # warm-cache second pass
            finally:
                results.append(self._finish(s))
        (h_seq, o_seq), (h_par, o_par) = results
        assert o_seq == o_par
        assert h_seq == h_par
        assert len(h_seq) > 0

    @needs_native
    def test_columnar_byte_identical(self, tmp_path, monkeypatch):
        monkeypatch.setattr(partition_mod, "MAX_PENDING_ROWS", 512)
        results = []
        for shards, sub in ((1, "seq"), (4, "par")):
            s = _mk_store(tmp_path / sub, shards, monkeypatch)
            try:
                for args in _columnar_batches():
                    s.add_rows_columnar(native.ColumnarRows(*args))
            finally:
                results.append(self._finish(s))
        (h_seq, o_seq), (h_par, o_par) = results
        assert o_seq == o_par
        assert h_seq == h_par

    def test_cardinality_limited_byte_identical(self, tmp_path, monkeypatch):
        """With a tight hourly budget the SAME series must win the
        admission race in both modes (limiter probes run in input order
        on the calling thread), so parts and drop counts stay equal."""
        results = []
        for shards, sub in ((1, "seq"), (4, "par")):
            s = _mk_store(tmp_path / sub, shards, monkeypatch,
                          max_hourly_series=12)
            try:
                s.add_rows(_legacy_rows())
                dropped = s.hourly_limiter.rows_dropped
            finally:
                h, o = self._finish(s)
                results.append((h, o, dropped))
        (h_seq, o_seq, d_seq), (h_par, o_par, d_par) = results
        assert o_seq == o_par
        assert d_seq == d_par > 0
        assert h_seq == h_par

    def test_multiwriter_merged_equality(self, tmp_path, monkeypatch):
        """Concurrent writers with pre-registered series: after
        force_merge the canonical merged part depends only on the row
        set, so the sharded store equals the sequential one."""
        def run(shards, sub):
            s = _mk_store(tmp_path / sub, shards, monkeypatch)
            try:
                # register every series first so metric ids don't depend
                # on which writer thread resolves first
                s.add_rows([({"__name__": "mw", "w": str(w), "i": str(i)},
                             T0 - 60_000 + w * 16 + i, 0.0)
                            for w in range(4) for i in range(16)])
                errs = []

                def writer(w):
                    try:
                        for j in range(1, 40):
                            s.add_rows([
                                ({"__name__": "mw", "w": str(w),
                                  "i": str(i)},
                                 T0 + j * 1000 + w, float(j))
                                for i in range(16)])
                    except BaseException as e:  # noqa: BLE001
                        errs.append(e)

                threads = [threading.Thread(target=writer, args=(w,),
                                            daemon=True) for w in range(4)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=60)
                assert not errs, errs
                s.force_flush()
                s.force_merge()
                rows = s.table.rows
            finally:
                data = os.path.join(s.path, "data")
                s.close()
            return _hash_tree(data), rows

        h_seq, r_seq = run(1, "seq")
        h_par, r_par = run(4, "par")
        assert r_seq == r_par == 4 * 16 + 4 * 39 * 16
        assert h_seq == h_par

    def test_spill_error_does_not_poison_partition(self, tmp_path,
                                                   monkeypatch):
        """A failing async pending conversion drops its batch with
        consistent bookkeeping (like a failed inline conversion) instead
        of wedging every later drain on the cached exception."""
        monkeypatch.setattr(partition_mod, "MAX_PENDING_ROWS", 32)
        s = _mk_store(tmp_path / "s", 4, monkeypatch)
        real = partition_mod._rows_to_inmemory_part
        calls = {"n": 0}

        def flaky(rows, *a, **kw):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("boom")
            return real(rows, *a, **kw)

        monkeypatch.setattr(partition_mod, "_rows_to_inmemory_part", flaky)
        err0 = metricslib.REGISTRY.counter(
            "vm_ingest_spill_errors_total").get()
        try:
            # 40 rows > cap: spilled to the pool, conversion fails; the
            # failure is logged+counted at the source, NOT re-raised into
            # unrelated readers/flushers
            s.add_rows([({"__name__": "pe", "i": str(i)}, T0 + i, float(i))
                        for i in range(40)])
            s.force_flush()
            # the partition is NOT poisoned: later ingest/flush/query work
            s.add_rows([({"__name__": "pe2", "i": str(i)}, T0 + i, 1.0)
                        for i in range(8)])
            s.force_flush()
            got = s.search_series(filters_from_dict({"__name__": "pe2"}),
                                  T0 - 10**6, T0 + 10**6)
            assert len(got) == 8
            assert s.table.rows == 8  # failed batch dropped, books balance
            assert metricslib.REGISTRY.counter(
                "vm_ingest_spill_errors_total").get() == err0 + 1
        finally:
            s.close()

    @needs_native
    def test_sharded_query_during_spill(self, tmp_path, monkeypatch):
        """Reads issued while async pending conversions are in flight
        see every ingested row exactly once."""
        monkeypatch.setattr(partition_mod, "MAX_PENDING_ROWS", 256)
        s = _mk_store(tmp_path / "s", 4, monkeypatch)
        try:
            total = 0
            for args in _columnar_batches():
                total += s.add_rows_columnar(native.ColumnarRows(*args))
                cols = s.search_columns(
                    filters_from_dict({"__name__": "cm"}),
                    T0 - 10**6, T0 + 10**10)
                assert cols.n_samples == total
            assert s.table.rows == total
        finally:
            s.close()


# -- generation-rotated caches ------------------------------------------------

class TestWorkingSetCache:
    def test_no_wipe_at_capacity(self):
        c = WorkingSetCache(4, "t")
        for i in range(4):
            c.put(i, i * 10)
        assert c.rotations == 0
        c.put(4, 40)  # overflow: rotates, does NOT wipe
        assert c.rotations == 1
        # every previously cached entry is still served (from prev gen)
        for i in range(5):
            assert c.get(i) == i * 10

    def test_promotion_keeps_working_set_alive(self):
        c = WorkingSetCache(2, "t")
        c.put("a", 1)
        c.put("b", 2)
        c.put("c", 3)          # rotation #1: cur={c}, prev={a,b}
        assert c.rotations == 1
        assert c.get("a") == 1  # promoted into cur
        c.put("d", 4)           # rotation #2: prev={a,c}... "a" survives
        assert c.get("a") == 1
        # an entry idle across two full generations is gone
        assert c.get("b") is None

    def test_len_bool_items_filter(self):
        c = WorkingSetCache(2, "t")
        assert not c
        c.put("a", 1)
        c.put("b", 2)
        c.put("c", 3)
        assert c and len(c) == 3          # distinct keys across both gens
        assert dict(c.items()) == {"a": 1, "b": 2, "c": 3}
        c.filter(lambda k, v: v != 2)
        assert c.get("b") is None and len(c) == 2
        c.clear()
        assert not c and len(c) == 0

    def test_put_overwrite_does_not_rotate(self):
        c = WorkingSetCache(2, "t")
        c.put("a", 1)
        c.put("b", 2)
        c.put("a", 9)  # overwrite of a resident key: no rotation
        assert c.rotations == 0
        assert c.get("a") == 9


@needs_storage
class TestIndexCacheRotation:
    def test_filter_cache_rotates_instead_of_wiping(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.setenv("VM_INGEST_SHARDS", "1")
        s = Storage(str(tmp_path / "s"))
        try:
            s.add_rows([({"__name__": f"fc{i}", "x": "1"}, T0, 1.0)
                        for i in range(6)])
            idb = s.idb
            idb.MAX_FILTER_CACHE = 2  # instance-level shrink
            f0 = filters_from_dict({"__name__": "fc0"})
            idb.search_metric_ids(f0, T0, T0 + 1000)
            # overflow the current generation with distinct selectors
            for i in range(1, 4):
                idb.search_metric_ids(
                    filters_from_dict({"__name__": f"fc{i}"}),
                    T0, T0 + 1000)
            # f0 rotated into the previous generation, NOT wiped: the
            # repeat is a cache hit
            h0 = idb.filter_cache_hits
            idb.search_metric_ids(f0, T0, T0 + 1000)
            assert idb.filter_cache_hits == h0 + 1
        finally:
            s.close()

    def test_filter_cache_counters_are_registry_backed(self, tmp_path,
                                                       monkeypatch):
        monkeypatch.setenv("VM_INGEST_SHARDS", "1")
        g0 = metricslib.REGISTRY.counter(
            'vm_cache_requests_total{type="indexdb/tagFilters"}').get()
        s = Storage(str(tmp_path / "s"))
        try:
            s.add_rows([({"__name__": "rc", "x": "1"}, T0, 1.0)])
            f = filters_from_dict({"__name__": "rc"})
            r0 = s.idb.filter_cache_requests
            s.idb.search_metric_ids(f, T0, T0 + 1000)
            s.idb.search_metric_ids(f, T0, T0 + 1000)
            assert s.idb.filter_cache_requests == r0 + 2
            assert s.idb.filter_cache_hits >= 1
            # the property shims are read-only views over Counters
            with pytest.raises(AttributeError):
                s.idb.filter_cache_requests = 0
            assert metricslib.REGISTRY.counter(
                'vm_cache_requests_total{type="indexdb/tagFilters"}'
            ).get() >= g0 + 2
        finally:
            s.close()

    def test_id_caches_survive_rotation(self, tmp_path, monkeypatch):
        monkeypatch.setenv("VM_INGEST_SHARDS", "1")
        s = Storage(str(tmp_path / "s"))
        try:
            s.add_rows([({"__name__": "idc", "i": str(i)}, T0, 1.0)
                        for i in range(8)])
            idb = s.idb
            idb._name_cache = WorkingSetCache(4, "test.name")
            mids = [int(m) for m in
                    idb.search_metric_ids(
                        filters_from_dict({"__name__": "idc"}),
                        T0, T0 + 1000)]
            for m in mids:        # fills past capacity: rotates, no wipe
                assert idb.get_metric_name_by_id(m) is not None
            assert idb._name_cache.rotations >= 1
            for m in mids:        # all still resolvable (cache or index)
                assert idb.get_metric_name_by_id(m) is not None
        finally:
            s.close()


# -- merge gate ---------------------------------------------------------------

class TestMergeGate:
    def test_admission_bounds_concurrency(self):
        gate = workpool.MergeGate(limit=1)
        order = []
        entered = threading.Event()
        release = threading.Event()

        def holder():
            with gate:
                order.append("A-in")
                entered.set()
                release.wait(10)
            order.append("A-out")

        def waiter():
            entered.wait(10)
            with gate:          # blocks until the holder releases
                order.append("B-in")

        a = threading.Thread(target=holder, daemon=True)
        b = threading.Thread(target=waiter, daemon=True)
        a.start()
        b.start()
        entered.wait(10)
        # B must be queued, not admitted
        deadline = time.monotonic() + 2
        while gate.pending == 0 and time.monotonic() < deadline:
            time.sleep(0.001)
        assert gate.active == 1 and gate.pending == 1
        assert order == ["A-in"]
        release.set()
        a.join(timeout=10)
        b.join(timeout=10)
        assert order == ["A-in", "A-out", "B-in"]
        assert gate.active == 0 and gate.pending == 0

    def test_env_sizing_and_metrics_exposed(self, monkeypatch):
        monkeypatch.setenv("VM_MERGE_WORKERS", "3")
        assert workpool.MergeGate().limit == 3
        monkeypatch.setenv("VM_MERGE_WORKERS", "junk")
        assert workpool.MergeGate().limit == (os.cpu_count() or 1)
        text = metricslib.REGISTRY.write_prometheus()
        assert "vm_merge_pending" in text
        assert "vm_merge_active" in text


# -- service-thread lifecycle + ingest metrics --------------------------------

@needs_storage
class TestIngestRuntime:
    def test_flusher_thread_joined_on_close(self, tmp_path, monkeypatch):
        monkeypatch.setenv("VM_INGEST_SHARDS", "2")
        s = Storage(str(tmp_path / "s"))
        flusher = s._flusher
        assert flusher.is_alive()
        s.close()
        assert not flusher.is_alive()

    def test_ingest_metrics_move(self, tmp_path, monkeypatch):
        monkeypatch.setenv("VM_INGEST_SHARDS", "2")
        rows0 = metricslib.REGISTRY.counter("vm_ingest_rows_total").get()
        res0 = metricslib.ingest_phase("resolve").get()
        s = Storage(str(tmp_path / "s"))
        try:
            s.add_rows([({"__name__": "im", "i": str(i)}, T0, float(i))
                        for i in range(10)])
            s.force_flush()
        finally:
            s.close()
        assert metricslib.REGISTRY.counter(
            "vm_ingest_rows_total").get() == rows0 + 10
        assert metricslib.ingest_phase("resolve").get() > res0
        assert metricslib.ingest_phase("flush").get() > 0
        text = metricslib.REGISTRY.write_prometheus()
        assert 'vm_ingest_phase_seconds_total{phase="register"}' in text
        assert "vm_ingest_shard_lock_wait_seconds_total" in text

    def test_shards_env_escape_hatch(self, tmp_path, monkeypatch):
        monkeypatch.setenv("VM_INGEST_SHARDS", "1")
        s = Storage(str(tmp_path / "s"))
        try:
            assert len(s._shards) == 1
            assert not workpool.ingest_parallel_enabled()
        finally:
            s.close()
        monkeypatch.setenv("VM_INGEST_SHARDS", "5")
        s = Storage(str(tmp_path / "s2"))
        try:
            assert len(s._shards) == 5
        finally:
            s.close()
