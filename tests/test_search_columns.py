"""Differential tests: columnar batched search (Storage.search_columns /
search_series) vs the per-block reference implementation
(Storage._search_series_blocks), across multi-part layouts, overlapping
flushes, duplicates, staleness markers and dedup intervals."""

import numpy as np
import pytest

from victoriametrics_tpu.ops.decimal import STALE_NAN
from victoriametrics_tpu.storage.storage import Storage
from victoriametrics_tpu.storage.tag_filters import TagFilter


@pytest.fixture
def store(tmp_path):
    st = Storage(str(tmp_path / "st"))
    yield st
    st.close()


def _ingest(st, rows):
    st.add_rows(rows)


def _filters(name):
    return [TagFilter(b"", name.encode())]


def _compare(st, filters, lo, hi, dedup=None):
    got = st.search_series(filters, lo, hi, dedup_interval_ms=dedup)
    want = st._search_series_blocks(filters, lo, hi, dedup_interval_ms=dedup)
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert g.raw_name == w.raw_name
        assert np.array_equal(g.timestamps, w.timestamps), g.metric_name
        assert np.array_equal(g.values.view(np.uint64),
                              w.values.view(np.uint64)), g.metric_name
    return got


def test_columnar_matches_blocks_basic(store):
    base = 1_700_000_000_000
    rows = []
    for i in range(50):
        for j in range(40):
            rows.append(({"__name__": "m", "i": str(i)},
                         base + j * 10_000, i + j * 0.25))
    _ingest(store, rows)
    store.force_flush()
    got = _compare(store, _filters("m"), base, base + 39 * 10_000)
    assert len(got) == 50


def test_columnar_range_clip(store):
    base = 1_700_000_000_000
    rows = [({"__name__": "m", "i": str(i)}, base + j * 1000, float(j))
            for i in range(8) for j in range(100)]
    _ingest(store, rows)
    store.force_flush()
    # interior range: blocks overhang on both sides
    _compare(store, _filters("m"), base + 25_500, base + 74_499)
    # range before/after all data
    assert store.search_series(_filters("m"), base - 10_000,
                               base - 1) == []


def test_columnar_multi_part_overlap(store):
    """Several flushed parts with interleaved timestamps force the per-row
    sort fix."""
    base = 1_700_000_000_000
    for wave in range(4):
        rows = [({"__name__": "ov", "i": str(i)},
                 base + (j * 4 + wave) * 1000, wave * 100.0 + j)
                for i in range(6) for j in range(30)]
        _ingest(store, rows)
        store.force_flush()  # each wave -> its own part
    _compare(store, _filters("ov"), base, base + 200_000)


def test_columnar_duplicate_timestamps(store):
    """Same (series, ts) in different parts: keep-last collapse."""
    base = 1_700_000_000_000
    rows1 = [({"__name__": "dup"}, base + j * 1000, 1.0) for j in range(20)]
    _ingest(store, rows1)
    store.force_flush()
    rows2 = [({"__name__": "dup"}, base + j * 1000, 2.0) for j in range(20)]
    _ingest(store, rows2)
    store.force_flush()
    got = _compare(store, _filters("dup"), base, base + 60_000)
    assert got[0].timestamps.size == 20


def test_columnar_dedup_interval(store):
    base = 1_700_000_000_000
    rows = [({"__name__": "dd", "i": str(i)}, base + j * 1000,
             float(j)) for i in range(5) for j in range(200)]
    _ingest(store, rows)
    store.force_flush()
    _compare(store, _filters("dd"), base, base + 300_000, dedup=10_000)


def test_columnar_stale_markers(store):
    base = 1_700_000_000_000
    rows = []
    for i in range(10):
        for j in range(30):
            v = STALE_NAN if (i == 3 and j % 7 == 0) else float(j)
            rows.append(({"__name__": "st", "i": str(i)}, base + j * 1000, v))
    _ingest(store, rows)
    store.force_flush()
    cols = store.search_columns(_filters("st"), base, base + 60_000)
    assert cols.stale_rows is not None
    assert int(cols.stale_rows.sum()) == 1
    got = _compare(store, _filters("st"), base, base + 60_000)
    stale_series = [g for g in got if b"3" in g.raw_name and g.maybe_stale]
    assert len(stale_series) >= 1
    cols.drop_stale_nans()
    assert cols.stale_rows is None
    # the stale row lost ceil(30/7)=5 samples
    assert int(cols.counts.min()) == 25


def test_columnar_unflushed_pending_and_memory(store):
    """pending rows + mem parts + file parts all feed one assembly."""
    base = 1_700_000_000_000
    rows = [({"__name__": "mix", "i": str(i)}, base + j * 1000, float(i + j))
            for i in range(7) for j in range(25)]
    _ingest(store, rows)
    store.force_flush()  # file part
    rows2 = [({"__name__": "mix", "i": str(i)}, base + (25 + j) * 1000,
              float(100 + j)) for i in range(7) for j in range(10)]
    _ingest(store, rows2)  # stays pending (no flush)
    _compare(store, _filters("mix"), base, base + 60_000)


def test_columnar_ragged_series(store):
    """Wildly different per-series lengths exercise the padded scatter."""
    rng = np.random.default_rng(7)
    base = 1_700_000_000_000
    rows = []
    for i in range(30):
        n = int(rng.integers(1, 120))
        for j in range(n):
            rows.append(({"__name__": "rag", "i": str(i)},
                         base + j * 1000, float(j * i)))
    _ingest(store, rows)
    store.force_flush()
    _compare(store, _filters("rag"), base, base + 200_000)


def test_columnar_max_series_limit(store):
    base = 1_700_000_000_000
    rows = [({"__name__": "lim", "i": str(i)}, base, 1.0) for i in range(20)]
    _ingest(store, rows)
    store.force_flush()
    with pytest.raises(ResourceWarning):
        store.search_columns(_filters("lim"), base - 1000, base + 1000,
                             max_series=5)


def test_columnar_specials_roundtrip(store):
    """NaN / +-Inf / huge+tiny decimals survive the native decode+convert."""
    base = 1_700_000_000_000
    vals = [1.5, float("nan"), float("inf"), float("-inf"), 0.0, 1e-15,
            123456789.123, -2.5e17, 0.001, 7.0]
    rows = [({"__name__": "sp"}, base + j * 1000, v)
            for j, v in enumerate(vals)]
    _ingest(store, rows)
    store.force_flush()
    _compare(store, _filters("sp"), base, base + 20_000)
