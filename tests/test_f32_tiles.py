"""f32 device tiles: rebased-value design differential bounds.

Real TPUs have no native float64, so device tiles there are float32 holding
REBASED values v - v0 with exact integer-mantissa rebasing on device (see
query/tpu_engine.py f32 design comment). These tests force an f32 engine on
the CPU backend and bound the device-vs-host-f64 error on adversarial data:
counters with a LARGE base (1e9+) and small increments — the case plain-f32
tiles would destroy (1e9 has ~64-unit ulp in f32; a 5m rate window moves by
~100s of units).

Reference precedent for lossy device numerics: the storage codec itself
quantizes values (lib/encoding/nearest_delta.go:15 precisionBits).
"""

import numpy as np
import pytest

from victoriametrics_tpu.ops import rollup_np
from victoriametrics_tpu.ops.rollup_np import RollupConfig
from victoriametrics_tpu.query import tpu_engine
from victoriametrics_tpu.query.tpu_engine import (
    TPUEngine, try_aggr_rollup_tpu, try_quantile_rollup_tpu, try_rollup_tpu,
    try_topk_rollup_tpu)
from victoriametrics_tpu.storage.metric_name import MetricName
from victoriametrics_tpu.storage.storage import SeriesData

START = 1_753_700_000_000
CFG = RollupConfig(start=START + 600_000, end=START + 1_800_000,
                   step=60_000, window=300_000)
BASE = 1.0e9  # large counter base: the f32 killer


def _series(n_series=96, n=140, base=BASE, resets=False, seed=3):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n_series):
        ts = np.arange(n, dtype=np.int64) * 15_000 + START
        ts = ts + rng.integers(-2000, 2000, n)
        ts.sort()
        v = base * (1 + i / 7) + np.cumsum(rng.integers(0, 50, n)) \
            .astype(np.float64)
        if resets and i % 3 == 0:
            p = int(rng.integers(n // 3, n))
            v[p:] -= v[p] - base / 1000  # reset near zero, then re-grow
        mn = MetricName.from_dict({"__name__": "m", "i": str(i)})
        out.append(SeriesData(mn, ts, v, raw_name=mn.marshal()))
    return out


def _host_rows(func, series):
    pairs = [(sd.timestamps, sd.values) for sd in series]
    return rollup_np.rollup_batch(func, pairs, CFG)


@pytest.fixture(scope="module")
def engine():
    return TPUEngine(value_dtype=np.float32, min_series=2)


def _assert_close(dev, host, rtol, label):
    dev = np.asarray(dev, dtype=np.float64)
    host = np.asarray(host, dtype=np.float64)
    assert dev.shape == host.shape, label
    np.testing.assert_array_equal(np.isnan(dev), np.isnan(host),
                                  err_msg=label)
    m = ~np.isnan(host)
    scale = np.maximum(np.abs(host[m]), 1e-3)
    err = np.abs(dev[m] - host[m]) / scale
    assert err.size == 0 or float(err.max()) < rtol, \
        f"{label}: max rel err {err.max():.3g} >= {rtol}"


# -- shift-invariant funcs run directly on rebased f32 tiles ---------------

@pytest.mark.parametrize("func,rtol", [
    ("rate", 1e-5), ("increase", 1e-5), ("delta", 1e-5), ("irate", 1e-5),
    ("idelta", 1e-5), ("changes", 1e-5), ("count_over_time", 1e-5),
    # variance centering subtracts the whole-series mean; window-local
    # spread is ~100x smaller than the rebased magnitude, so the E[x^2]
    # cancellation costs ~1 decimal digit extra
    ("stddev_over_time", 1e-4),
    # least-squares slope: the moment-sum cancellation amplifies f32
    # rounding ~10x beyond the plain window arithmetic
    ("deriv", 1e-3),
])
def test_direct_funcs_large_base(engine, func, rtol):
    series = _series()
    rows = try_rollup_tpu(engine, func, series, CFG, ())
    assert rows is not None, "device path must engage on f32 tiles"
    host = _host_rows(func, series)
    # bound: one f32 rounding of the REBASED magnitude, amplified by the
    # window arithmetic — 1e-5 relative leaves ~100x headroom over 2^-23
    _assert_close(np.stack(rows), host, rtol, func)


def test_counter_resets_small_base(engine):
    """Resets at small magnitude (< 2^24): the f32 reset correction stays
    exact enough — classification (8x-drop rule, rollup.go:921) and values
    must track the host."""
    series = _series(base=1.0e5, resets=True, seed=9)
    for func in ("rate", "increase"):
        rows = try_rollup_tpu(engine, func, series, CFG, ())
        assert rows is not None
        _assert_close(np.stack(rows), _host_rows(func, series), 1e-4,
                      f"{func}+resets")


def test_counter_resets_large_base_falls_back(engine):
    """A reset from a 1e9 base pushes the REBASED magnitude past 2^24:
    every value-dependent func must refuse the tile (host f64 handles it);
    value-free funcs still run."""
    # distinct seed: the tile fingerprint keys on (name, count, last ts)
    # and would otherwise collide with the small-base variant's tile
    series = _series(resets=True, seed=11)  # base 1e9, resets to ~1e6
    for func in ("rate", "increase", "delta", "min_over_time"):
        assert try_rollup_tpu(engine, func, series, CFG, ()) is None, func
    gids = np.zeros(len(series), np.int32)
    assert try_aggr_rollup_tpu(engine, "sum", "rate", series, gids, 1,
                               CFG) is None
    # value-free funcs are immune to value error: stay on device
    rows = try_rollup_tpu(engine, "count_over_time", series, CFG, ())
    assert rows is not None
    _assert_close(np.stack(rows), _host_rows("count_over_time", series),
                  1e-9, "count on wide-range tile")


def test_fractional_scale_wide_mantissa_falls_back(engine):
    """Value range < 2^24 but MANTISSA range >= 2^24 (fractional decimal
    scale 10^-3): the one f32 rounding happens on the rebased mantissa, so
    the value-space gate alone would silently cost integer exactness for
    equality-sensitive funcs. Value-dependent funcs must refuse the tile;
    value-free funcs still run (round-4 advisor finding)."""
    rng = np.random.default_rng(21)
    n = 140
    series = []
    for i in range(8):
        ts = np.arange(n, dtype=np.int64) * 15_000 + START
        # 3-decimal counter reaching ~21k: mantissa range ~2.1e7 > 2^24,
        # value range far below 2^24
        v = np.round(np.cumsum(rng.uniform(100.0, 200.0, n)), 3)
        mn = MetricName.from_dict({"__name__": "frac", "i": str(i)})
        series.append(SeriesData(mn, ts, v, raw_name=mn.marshal()))
    for func in ("changes", "rate", "delta"):
        assert try_rollup_tpu(engine, func, series, CFG, ()) is None, func
    rows = try_rollup_tpu(engine, "count_over_time", series, CFG, ())
    assert rows is not None
    _assert_close(np.stack(rows), _host_rows("count_over_time", series),
                  1e-9, "count on wide-mantissa tile")


# -- affine funcs get per-series f64 addback -------------------------------

@pytest.mark.parametrize("func", ["min_over_time", "max_over_time",
                                  "avg_over_time", "first_over_time",
                                  "last_over_time", "default_rollup"])
def test_affine_addback_large_base(engine, func):
    series = _series()
    rows = try_rollup_tpu(engine, func, series, CFG, ())
    assert rows is not None, "affine funcs run via host addback"
    host = _host_rows(func, series)
    # addback restores absolute scale in f64; residual error is the f32
    # rounding of the rebased part relative to the ABSOLUTE value — tiny
    _assert_close(np.stack(rows), host, 1e-6, func)


# -- gating: what f32 tiles must NOT run -----------------------------------

def test_f32_gating(engine):
    series = _series(n_series=8)
    # sum_over_time needs n*v0 — not affine, must fall back
    assert try_rollup_tpu(engine, "sum_over_time", series, CFG, ()) is None
    # fused aggregation crosses series with different v0: affine funcs
    # cannot run fused
    gids = np.zeros(len(series), np.int32)
    assert try_aggr_rollup_tpu(engine, "sum", "last_over_time", series,
                               gids, 1, CFG) is None
    # topk selection compares absolutes across series
    assert try_topk_rollup_tpu(engine, "topk", 3.0, "max_over_time",
                               series, CFG) is None
    # f64 engines are unrestricted
    e64 = TPUEngine(value_dtype=np.float64, min_series=2)
    assert e64.func_mode("sum_over_time", per_series=False) == "direct"


def test_fused_aggr_rate_large_base(engine):
    """The headline shape: sum by (g)(rate(counter)) fused on f32 tiles."""
    series = _series(n_series=96)
    gids = np.array([i % 5 for i in range(len(series))], np.int32)
    out = try_aggr_rollup_tpu(engine, "sum", "rate", series, gids, 5, CFG)
    assert out is not None
    host_rows = _host_rows("rate", series)
    T = host_rows.shape[1]
    expect = np.zeros((5, T))
    for g in range(5):
        sub = host_rows[gids == g]
        expect[g] = np.where(np.isnan(sub).all(axis=0), np.nan,
                             np.nansum(sub, axis=0))
    _assert_close(out, expect, 1e-5, "sum(rate) fused")


def test_quantile_rate_large_base(engine):
    from victoriametrics_tpu.query.tpu_engine import group_slots
    series = _series(n_series=48, seed=5)
    gids = np.array([i % 3 for i in range(len(series))], np.int32)
    slots, max_group = group_slots(gids, 3)
    out = try_quantile_rollup_tpu(engine, 0.5, "rate", series, gids, 3,
                                  CFG, slots, max_group)
    assert out is not None
    host_rows = _host_rows("rate", series)
    T = host_rows.shape[1]
    expect = np.full((3, T), np.nan)
    for g in range(3):
        sub = host_rows[gids == g]
        for t in range(T):
            col = sub[:, t]
            if not np.isnan(col).all():
                expect[g, t] = np.nanquantile(col, 0.5)
    _assert_close(out, expect, 1e-5, "median(rate) fused")


def test_auto_dtype_on_cpu():
    # this suite runs on the CPU backend (conftest pins it): auto = f64
    assert np.dtype(tpu_engine.auto_value_dtype()) == np.float64
    assert np.dtype(TPUEngine().value_dtype) == np.float64
