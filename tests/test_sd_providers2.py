"""Round-5 SD providers (nomad, dockerswarm, eureka, openstack,
digitalocean) against local mock APIs (the reference's discovery
fixtures, lib/promscrape/discovery/*/..._test.go)."""

from victoriametrics_tpu.httpapi.server import HTTPServer, Response
from victoriametrics_tpu.ingest import discovery


def _srv(routes):
    srv = HTTPServer("127.0.0.1", 0)
    for path, payload in routes.items():
        srv.route(path, (lambda p: (lambda r: Response.json(p)))(payload))
    srv.start()
    return srv


class TestNomadSD:
    def test_services(self):
        srv = _srv({
            "/v1/services": [
                {"Namespace": "default",
                 "Services": [{"ServiceName": "redis", "Tags": []}]}],
            "/v1/service/redis": [
                {"ID": "sid1", "ServiceName": "redis",
                 "Address": "10.2.0.5", "Port": 6379, "NodeID": "n1",
                 "Datacenter": "dc1", "JobID": "cache",
                 "AllocID": "a1", "Namespace": "default",
                 "Tags": ["db", "tier=back"]}],
        })
        try:
            out = discovery.nomad_sd(
                {"server": f"127.0.0.1:{srv.port}"})
            assert len(out) == 1
            tgt, meta = out[0]
            assert tgt == "10.2.0.5:6379"
            assert meta["__meta_nomad_service"] == "redis"
            assert meta["__meta_nomad_dc"] == "dc1"
            assert meta["__meta_nomad_service_job_id"] == "cache"
            assert meta["__meta_nomad_tags"] == ",db,tier=back,"
            assert meta["__meta_nomad_tag_tier"] == "back"
            assert meta["__meta_nomad_tagpresent_db"] == "true"
        finally:
            srv.stop()


class TestDockerswarmSD:
    NODES = [{"ID": "n1", "Spec": {"Role": "manager",
                                   "Availability": "active",
                                   "Labels": {"zone": "a"}},
              "Description": {"Hostname": "h1",
                              "Platform": {"Architecture": "x86_64",
                                           "OS": "linux"},
                              "Engine": {"EngineVersion": "24.0"}},
              "Status": {"State": "ready", "Addr": "10.3.0.1"}}]
    SERVICES = [{"ID": "s1",
                 "Spec": {"Name": "web", "Mode": {"Replicated": {}},
                          "Labels": {"team": "x"}},
                 "Endpoint": {"VirtualIPs": [
                     {"NetworkID": "net1", "Addr": "10.0.0.9/24"}]}}]
    TASKS = [{"ID": "t1", "ServiceID": "s1", "NodeID": "n1", "Slot": 1,
              "DesiredState": "running", "Status": {"State": "running"},
              "Spec": {"ContainerSpec": {"Labels": {"com.x": "1"}}},
              "NetworksAttachments": [
                  {"Addresses": ["10.0.0.12/24"]}]}]

    def _srv(self):
        return _srv({"/nodes": self.NODES, "/services": self.SERVICES,
                     "/tasks": self.TASKS})

    def test_role_tasks(self):
        srv = self._srv()
        try:
            out = discovery.dockerswarm_sd(
                {"host": f"http://127.0.0.1:{srv.port}", "port": 9100})
            assert out[0][0] == "10.0.0.12:9100"
            meta = out[0][1]
            assert meta["__meta_dockerswarm_service_name"] == "web"
            assert meta["__meta_dockerswarm_node_hostname"] == "h1"
            assert meta["__meta_dockerswarm_task_state"] == "running"
            assert meta["__meta_dockerswarm_container_label_com_x"] == "1"
        finally:
            srv.stop()

    def test_role_services_and_nodes(self):
        srv = self._srv()
        try:
            svc = discovery.dockerswarm_sd(
                {"host": f"http://127.0.0.1:{srv.port}",
                 "role": "services"})
            assert svc[0][0] == "10.0.0.9:80"
            assert svc[0][1]["__meta_dockerswarm_service_label_team"] \
                == "x"
            nodes = discovery.dockerswarm_sd(
                {"host": f"http://127.0.0.1:{srv.port}", "role": "nodes",
                 "port": 9323})
            assert nodes[0][0] == "10.3.0.1:9323"
            assert nodes[0][1]["__meta_dockerswarm_node_role"] \
                == "manager"
            assert nodes[0][1]["__meta_dockerswarm_node_label_zone"] \
                == "a"
        finally:
            srv.stop()


class TestEurekaSD:
    def test_apps(self):
        srv = _srv({"/eureka/v2/apps": {"applications": {"application": [
            {"name": "CART", "instance": [{
                "instanceId": "i-1", "hostName": "cart-1.local",
                "ipAddr": "10.4.0.2", "status": "UP",
                "port": {"$": 8081, "@enabled": "true"},
                "vipAddress": "cart", "countryId": 1,
                "dataCenterInfo": {"name": "MyOwn"},
                "metadata": {"zone": "b"},
                "homePageUrl": "http://cart-1.local/"}]}]}}})
        try:
            out = discovery.eureka_sd(
                {"server": f"127.0.0.1:{srv.port}/eureka/v2"})
            assert len(out) == 1
            tgt, meta = out[0]
            assert tgt == "cart-1.local:8081"
            assert meta["__meta_eureka_app_name"] == "CART"
            assert meta["__meta_eureka_app_instance_status"] == "UP"
            assert meta["__meta_eureka_app_instance_metadata_zone"] == "b"
            assert meta["__meta_eureka_app_instance_port_enabled"] \
                == "true"
        finally:
            srv.stop()


class TestOpenstackSD:
    def test_instances(self):
        srv = HTTPServer("127.0.0.1", 0)

        def tokens(r):
            resp = Response.json({"token": {"catalog": [
                {"type": "compute", "endpoints": [
                    {"interface": "public",
                     "url": f"http://127.0.0.1:{srv.port}/compute"}]}]}})
            resp.headers["X-Subject-Token"] = "tok123"
            return resp
        srv.route("/identity/auth/tokens", tokens)
        srv.route("/compute/servers/detail", lambda r: Response.json(
            {"servers": [{
                "id": "vm1", "name": "web-1", "status": "ACTIVE",
                "tenant_id": "p1", "user_id": "u1",
                "flavor": {"original_name": "m1.small"},
                "metadata": {"role": "web"},
                "addresses": {"private": [{"addr": "192.168.1.5"}]}}]}))
        srv.start()
        try:
            out = discovery.openstack_sd({
                "identity_endpoint":
                    f"http://127.0.0.1:{srv.port}/identity",
                "username": "u", "password": "p",
                "project_name": "demo", "port": 9100})
            assert out == [("192.168.1.5:9100", {
                "__meta_openstack_instance_id": "vm1",
                "__meta_openstack_instance_name": "web-1",
                "__meta_openstack_instance_status": "ACTIVE",
                "__meta_openstack_instance_flavor": "m1.small",
                "__meta_openstack_project_id": "p1",
                "__meta_openstack_user_id": "u1",
                "__meta_openstack_tag_role": "web",
                "__meta_openstack_address_pool": "private",
                "__meta_openstack_private_ip": "192.168.1.5"})]
        finally:
            srv.stop()


class TestDigitaloceanSD:
    def test_droplets_with_pagination(self):
        srv = HTTPServer("127.0.0.1", 0)
        page2 = {"droplets": [{
            "id": 2, "name": "d2", "status": "active",
            "image": {"slug": "ubuntu", "name": "Ubuntu"},
            "region": {"slug": "nyc1"}, "size": {"slug": "s-1vcpu"},
            "tags": ["web"], "features": ["ipv6"],
            "networks": {"v4": [
                {"type": "public", "ip_address": "1.2.3.5"}]}}]}
        page1 = {"droplets": [{
            "id": 1, "name": "d1", "status": "active",
            "image": {"slug": "deb", "name": "Debian"},
            "region": {"slug": "fra1"}, "size": {"slug": "s-2vcpu"},
            "tags": [], "features": [],
            "networks": {"v4": [
                {"type": "public", "ip_address": "1.2.3.4"},
                {"type": "private", "ip_address": "10.9.0.4"}]}}]}

        def h(r):
            if r.arg("page") == "2":
                return Response.json(page2)
            p1 = dict(page1)
            p1["links"] = {"pages": {"next":
                f"http://127.0.0.1:{srv.port}/v2/droplets?page=2"}}
            return Response.json(p1)
        srv.route("/v2/droplets", h)
        srv.start()
        try:
            out = discovery.digitalocean_sd(
                {"server": f"http://127.0.0.1:{srv.port}",
                 "bearer_token": "tk", "port": 9100})
            assert [t for t, _ in out] == ["1.2.3.4:9100", "1.2.3.5:9100"]
            m1 = out[0][1]
            assert m1["__meta_digitalocean_private_ipv4"] == "10.9.0.4"
            assert m1["__meta_digitalocean_region"] == "fra1"
            m2 = out[1][1]
            assert m2["__meta_digitalocean_tags"] == ",web,"
            assert m2["__meta_digitalocean_features"] == ",ipv6,"
        finally:
            srv.stop()


def test_all_providers_registered():
    for key in ("nomad_sd_configs", "dockerswarm_sd_configs",
                "eureka_sd_configs", "openstack_sd_configs",
                "digitalocean_sd_configs"):
        assert key in discovery.PROVIDERS
