"""Round-5 SD providers (nomad, dockerswarm, eureka, openstack,
digitalocean) against local mock APIs (the reference's discovery
fixtures, lib/promscrape/discovery/*/..._test.go)."""

from victoriametrics_tpu.httpapi.server import HTTPServer, Response
from victoriametrics_tpu.ingest import discovery


def _srv(routes):
    srv = HTTPServer("127.0.0.1", 0)
    for path, payload in routes.items():
        srv.route(path, (lambda p: (lambda r: Response.json(p)))(payload))
    srv.start()
    return srv


class TestNomadSD:
    def test_services(self):
        srv = _srv({
            "/v1/services": [
                {"Namespace": "default",
                 "Services": [{"ServiceName": "redis", "Tags": []}]}],
            "/v1/service/redis": [
                {"ID": "sid1", "ServiceName": "redis",
                 "Address": "10.2.0.5", "Port": 6379, "NodeID": "n1",
                 "Datacenter": "dc1", "JobID": "cache",
                 "AllocID": "a1", "Namespace": "default",
                 "Tags": ["db", "tier=back"]}],
        })
        try:
            out = discovery.nomad_sd(
                {"server": f"127.0.0.1:{srv.port}"})
            assert len(out) == 1
            tgt, meta = out[0]
            assert tgt == "10.2.0.5:6379"
            assert meta["__meta_nomad_service"] == "redis"
            assert meta["__meta_nomad_dc"] == "dc1"
            assert meta["__meta_nomad_service_job_id"] == "cache"
            assert meta["__meta_nomad_tags"] == ",db,tier=back,"
            assert meta["__meta_nomad_tag_tier"] == "back"
            assert meta["__meta_nomad_tagpresent_db"] == "true"
        finally:
            srv.stop()


class TestDockerswarmSD:
    NODES = [{"ID": "n1", "Spec": {"Role": "manager",
                                   "Availability": "active",
                                   "Labels": {"zone": "a"}},
              "Description": {"Hostname": "h1",
                              "Platform": {"Architecture": "x86_64",
                                           "OS": "linux"},
                              "Engine": {"EngineVersion": "24.0"}},
              "Status": {"State": "ready", "Addr": "10.3.0.1"}}]
    SERVICES = [{"ID": "s1",
                 "Spec": {"Name": "web", "Mode": {"Replicated": {}},
                          "Labels": {"team": "x"}},
                 "Endpoint": {"VirtualIPs": [
                     {"NetworkID": "net1", "Addr": "10.0.0.9/24"}]}}]
    TASKS = [{"ID": "t1", "ServiceID": "s1", "NodeID": "n1", "Slot": 1,
              "DesiredState": "running", "Status": {"State": "running"},
              "Spec": {"ContainerSpec": {"Labels": {"com.x": "1"}}},
              "NetworksAttachments": [
                  {"Addresses": ["10.0.0.12/24"]}]}]

    def _srv(self):
        return _srv({"/nodes": self.NODES, "/services": self.SERVICES,
                     "/tasks": self.TASKS})

    def test_role_tasks(self):
        srv = self._srv()
        try:
            out = discovery.dockerswarm_sd(
                {"host": f"http://127.0.0.1:{srv.port}", "port": 9100})
            assert out[0][0] == "10.0.0.12:9100"
            meta = out[0][1]
            assert meta["__meta_dockerswarm_service_name"] == "web"
            assert meta["__meta_dockerswarm_node_hostname"] == "h1"
            assert meta["__meta_dockerswarm_task_state"] == "running"
            assert meta["__meta_dockerswarm_container_label_com_x"] == "1"
        finally:
            srv.stop()

    def test_role_services_and_nodes(self):
        srv = self._srv()
        try:
            svc = discovery.dockerswarm_sd(
                {"host": f"http://127.0.0.1:{srv.port}",
                 "role": "services"})
            assert svc[0][0] == "10.0.0.9:80"
            assert svc[0][1]["__meta_dockerswarm_service_label_team"] \
                == "x"
            nodes = discovery.dockerswarm_sd(
                {"host": f"http://127.0.0.1:{srv.port}", "role": "nodes",
                 "port": 9323})
            assert nodes[0][0] == "10.3.0.1:9323"
            assert nodes[0][1]["__meta_dockerswarm_node_role"] \
                == "manager"
            assert nodes[0][1]["__meta_dockerswarm_node_label_zone"] \
                == "a"
        finally:
            srv.stop()


class TestEurekaSD:
    def test_apps(self):
        srv = _srv({"/eureka/v2/apps": {"applications": {"application": [
            {"name": "CART", "instance": [{
                "instanceId": "i-1", "hostName": "cart-1.local",
                "ipAddr": "10.4.0.2", "status": "UP",
                "port": {"$": 8081, "@enabled": "true"},
                "vipAddress": "cart", "countryId": 1,
                "dataCenterInfo": {"name": "MyOwn"},
                "metadata": {"zone": "b"},
                "homePageUrl": "http://cart-1.local/"}]}]}}})
        try:
            out = discovery.eureka_sd(
                {"server": f"127.0.0.1:{srv.port}/eureka/v2"})
            assert len(out) == 1
            tgt, meta = out[0]
            assert tgt == "cart-1.local:8081"
            assert meta["__meta_eureka_app_name"] == "CART"
            assert meta["__meta_eureka_app_instance_status"] == "UP"
            assert meta["__meta_eureka_app_instance_metadata_zone"] == "b"
            assert meta["__meta_eureka_app_instance_port_enabled"] \
                == "true"
        finally:
            srv.stop()


class TestOpenstackSD:
    def test_instances(self):
        srv = HTTPServer("127.0.0.1", 0)

        def tokens(r):
            resp = Response.json({"token": {"catalog": [
                {"type": "compute", "endpoints": [
                    {"interface": "public",
                     "url": f"http://127.0.0.1:{srv.port}/compute"}]}]}})
            resp.headers["X-Subject-Token"] = "tok123"
            return resp
        srv.route("/identity/auth/tokens", tokens)
        srv.route("/compute/servers/detail", lambda r: Response.json(
            {"servers": [{
                "id": "vm1", "name": "web-1", "status": "ACTIVE",
                "tenant_id": "p1", "user_id": "u1",
                "flavor": {"original_name": "m1.small"},
                "metadata": {"role": "web"},
                "addresses": {"private": [{"addr": "192.168.1.5"}]}}]}))
        srv.start()
        try:
            out = discovery.openstack_sd({
                "identity_endpoint":
                    f"http://127.0.0.1:{srv.port}/identity",
                "username": "u", "password": "p",
                "project_name": "demo", "port": 9100})
            assert out == [("192.168.1.5:9100", {
                "__meta_openstack_instance_id": "vm1",
                "__meta_openstack_instance_name": "web-1",
                "__meta_openstack_instance_status": "ACTIVE",
                "__meta_openstack_instance_flavor": "m1.small",
                "__meta_openstack_project_id": "p1",
                "__meta_openstack_user_id": "u1",
                "__meta_openstack_tag_role": "web",
                "__meta_openstack_address_pool": "private",
                "__meta_openstack_private_ip": "192.168.1.5"})]
        finally:
            srv.stop()


class TestDigitaloceanSD:
    def test_droplets_with_pagination(self):
        srv = HTTPServer("127.0.0.1", 0)
        page2 = {"droplets": [{
            "id": 2, "name": "d2", "status": "active",
            "image": {"slug": "ubuntu", "name": "Ubuntu"},
            "region": {"slug": "nyc1"}, "size": {"slug": "s-1vcpu"},
            "tags": ["web"], "features": ["ipv6"],
            "networks": {"v4": [
                {"type": "public", "ip_address": "1.2.3.5"}]}}]}
        page1 = {"droplets": [{
            "id": 1, "name": "d1", "status": "active",
            "image": {"slug": "deb", "name": "Debian"},
            "region": {"slug": "fra1"}, "size": {"slug": "s-2vcpu"},
            "tags": [], "features": [],
            "networks": {"v4": [
                {"type": "public", "ip_address": "1.2.3.4"},
                {"type": "private", "ip_address": "10.9.0.4"}]}}]}

        def h(r):
            if r.arg("page") == "2":
                return Response.json(page2)
            p1 = dict(page1)
            p1["links"] = {"pages": {"next":
                f"http://127.0.0.1:{srv.port}/v2/droplets?page=2"}}
            return Response.json(p1)
        srv.route("/v2/droplets", h)
        srv.start()
        try:
            out = discovery.digitalocean_sd(
                {"server": f"http://127.0.0.1:{srv.port}",
                 "bearer_token": "tk", "port": 9100})
            assert [t for t, _ in out] == ["1.2.3.4:9100", "1.2.3.5:9100"]
            m1 = out[0][1]
            assert m1["__meta_digitalocean_private_ipv4"] == "10.9.0.4"
            assert m1["__meta_digitalocean_region"] == "fra1"
            m2 = out[1][1]
            assert m2["__meta_digitalocean_tags"] == ",web,"
            assert m2["__meta_digitalocean_features"] == ",ipv6,"
        finally:
            srv.stop()


def test_all_providers_registered():
    for key in ("nomad_sd_configs", "dockerswarm_sd_configs",
                "eureka_sd_configs", "openstack_sd_configs",
                "digitalocean_sd_configs"):
        assert key in discovery.PROVIDERS


class TestConsulagentSD:
    def test_agent_services(self):
        srv = _srv({
            "/v1/agent/self": {"Member": {"Name": "node1",
                                          "Addr": "10.5.0.1"},
                               "Config": {"Datacenter": "dc1"}},
            "/v1/agent/services": {
                "redis-1": {"ID": "redis-1", "Service": "redis",
                            "Address": "10.5.0.2", "Port": 6379,
                            "Tags": ["primary"],
                            "Meta": {"redis_version": "7"}}},
        })
        try:
            out = discovery.consulagent_sd(
                {"server": f"127.0.0.1:{srv.port}"})
            assert out[0][0] == "10.5.0.2:6379"
            meta = out[0][1]
            assert meta["__meta_consulagent_service"] == "redis"
            assert meta["__meta_consulagent_dc"] == "dc1"
            assert meta["__meta_consulagent_node"] == "node1"
            assert meta["__meta_consulagent_tag_primary"] == "primary"
            assert meta["__meta_consulagent_service_metadata_"
                        "redis_version"] == "7"
            # service filter
            assert discovery.consulagent_sd(
                {"server": f"127.0.0.1:{srv.port}",
                 "services": ["other"]}) == []
        finally:
            srv.stop()


class TestHetznerSD:
    def test_hcloud_pagination(self):
        srv = HTTPServer("127.0.0.1", 0)
        page = {1: {"servers": [{
            "id": 7, "name": "web-1", "status": "running",
            "public_net": {"ipv4": {"ip": "5.6.7.8"}},
            "datacenter": {"name": "fsn1-dc14",
                           "location": {"name": "fsn1",
                                        "network_zone": "eu-central"}},
            "server_type": {"name": "cx11", "cores": 1,
                            "cpu_type": "shared", "memory": 2,
                            "disk": 20},
            "image": {"name": "ubuntu-22.04", "os_flavor": "ubuntu",
                      "os_version": "22.04"},
            "labels": {"env": "prod"}}],
            "meta": {"pagination": {"next_page": 2}}},
            2: {"servers": [], "meta": {"pagination": {}}}}

        def h(r):
            return Response.json(page[int(r.arg("page") or 1)])
        srv.route("/v1/servers", h)
        srv.start()
        try:
            out = discovery.hetzner_sd(
                {"endpoint": f"http://127.0.0.1:{srv.port}",
                 "bearer_token": "tk", "port": 9100})
            assert out == [("5.6.7.8:9100", out[0][1])]
            meta = out[0][1]
            assert meta["__meta_hetzner_hcloud_server_type"] == "cx11"
            assert meta["__meta_hetzner_hcloud_label_env"] == "prod"
            assert meta["__meta_hetzner_hcloud_labelpresent_env"] \
                == "true"
            assert meta["__meta_hetzner_hcloud_datacenter_location_"
                        "network_zone"] == "eu-central"
        finally:
            srv.stop()


class TestVultrSD:
    def test_instances(self):
        srv = _srv({"/v2/instances": {"instances": [{
            "id": "i-1", "label": "db", "hostname": "db-1",
            "os": "Ubuntu", "os_id": 1743, "region": "ewr",
            "plan": "vc2-1c-1gb", "main_ip": "45.1.2.3",
            "internal_ip": "10.1.1.1", "v6_main_ip": "::1",
            "server_status": "ok", "vcpu_count": 1, "ram": 1024,
            "disk": 25, "allowed_bandwidth": 1000,
            "features": ["ipv6"], "tags": ["db"]}],
            "meta": {"links": {"next": ""}}}})
        try:
            out = discovery.vultr_sd(
                {"endpoint": f"http://127.0.0.1:{srv.port}",
                 "bearer_token": "tk", "port": 9100})
            assert out[0][0] == "45.1.2.3:9100"
            meta = out[0][1]
            assert meta["__meta_vultr_instance_plan"] == "vc2-1c-1gb"
            assert meta["__meta_vultr_instance_tags"] == ",db,"
            assert meta["__meta_vultr_instance_ram_mb"] == "1024"
        finally:
            srv.stop()


class TestMarathonSD:
    def test_apps_tasks(self):
        srv = _srv({"/v2/apps": {"apps": [{
            "id": "/web", "labels": {"team": "x"},
            "container": {"docker": {"image": "nginx:1"}},
            "portDefinitions": [{"labels": {"metrics": "/metrics"}}],
            "tasks": [{"id": "web.t1", "host": "10.6.0.1",
                       "ports": [31001]}]}]}})
        try:
            out = discovery.marathon_sd(
                {"servers": [f"http://127.0.0.1:{srv.port}"]})
            assert out[0][0] == "10.6.0.1:31001"
            meta = out[0][1]
            assert meta["__meta_marathon_app"] == "/web"
            assert meta["__meta_marathon_image"] == "nginx:1"
            assert meta["__meta_marathon_app_label_team"] == "x"
            assert meta["__meta_marathon_port_definition_label_"
                        "metrics"] == "/metrics"
        finally:
            srv.stop()


class TestPuppetdbSD:
    def test_resources(self):
        from victoriametrics_tpu.httpapi.server import HTTPServer, Response

        srv = HTTPServer("127.0.0.1", 0)
        seen = []

        def h(r):
            import json as _j
            seen.append(_j.loads(r.body))
            return Response.json([{
                "certname": "agent1.local", "environment": "production",
                "exported": False, "file": "/etc/pp/site.pp",
                "resource": "abc123", "tags": ["class", "apache"],
                "parameters": {"port": 8080}}])
        srv.route("/pdb/query/v4", h)
        srv.start()
        try:
            out = discovery.puppetdb_sd({
                "url": f"http://127.0.0.1:{srv.port}",
                "query": 'resources { type = "Class" }',
                "port": 9100, "include_parameters": True})
            assert seen[0]["query"] == 'resources { type = "Class" }'
            assert out[0][0] == "agent1.local:9100"
            meta = out[0][1]
            assert meta["__meta_puppetdb_environment"] == "production"
            assert meta["__meta_puppetdb_exported"] == "false"
            assert meta["__meta_puppetdb_parameter_port"] == "8080"
            assert meta["__meta_puppetdb_tags"] == ",class,apache,"
        finally:
            srv.stop()


class TestOvhcloudSD:
    def test_vps_with_signature(self):
        seen_headers = []
        srv = HTTPServer("127.0.0.1", 0)
        srv.route("/1.0/auth/time", lambda r: Response.json(1_700_000_000))

        def vps_list(r):
            seen_headers.append({k.lower(): v
                                 for k, v in r.headers.items()})
            return Response.json(["vps-a1.vps.ovh.net"])
        srv.route("/1.0/vps", vps_list)
        srv.route("/1.0/vps/vps-a1.vps.ovh.net", lambda r: Response.json({
            "name": "vps-a1.vps.ovh.net", "displayName": "my-vps",
            "cluster": "cluster_021", "state": "running", "zone": "zone",
            "memoryLimit": 2048,
            "model": {"name": "vps-starter", "disk": 20, "memory": 2048,
                      "vcore": 1, "maximumAdditionnalIp": 16,
                      "version": "2019v1"}}))
        srv.route("/1.0/vps/vps-a1.vps.ovh.net/ips",
                  lambda r: Response.json(["139.99.1.2", "2001:41d0::1"]))
        srv.start()
        try:
            out = discovery.ovhcloud_sd({
                "endpoint": f"http://127.0.0.1:{srv.port}/1.0",
                "application_key": "ak", "application_secret": "as",
                "consumer_key": "ck", "port": 9100})
            assert out[0][0] == "139.99.1.2:9100"
            meta = out[0][1]
            assert meta["__meta_ovhcloud_vps_model_name"] == "vps-starter"
            assert meta["__meta_ovhcloud_vps_ipv4"] == "139.99.1.2"
            assert meta["__meta_ovhcloud_vps_ipv6"] == "2001:41d0::1"
            h = seen_headers[0]
            assert h.get("x-ovh-application") == "ak"
            assert h.get("x-ovh-consumer") == "ck"
            assert h.get("x-ovh-signature", "").startswith("$1$")
            # signature reproducible from the documented formula
            import hashlib
            url = f"http://127.0.0.1:{srv.port}/1.0/vps"
            ts = h["x-ovh-timestamp"]
            want = hashlib.sha1(
                f"as+ck+GET+{url}++{ts}".encode()).hexdigest()
            assert h["x-ovh-signature"] == f"$1${want}"
        finally:
            srv.stop()

    def test_dedicated_server(self):
        srv = _srv({
            "/1.0/auth/time": 1_700_000_000,
            "/1.0/dedicated/server": ["ns1.ip-1-2-3.eu"],
            "/1.0/dedicated/server/ns1.ip-1-2-3.eu": {
                "name": "ns1.ip-1-2-3.eu", "serverId": 42,
                "state": "ok", "os": "debian12", "datacenter": "gra1",
                "rack": "R01", "reverse": "ns1.ip-1-2-3.eu",
                "commercialRange": "rise-1", "linkSpeed": 1000,
                "supportLevel": "pro", "noIntervention": False},
            "/1.0/dedicated/server/ns1.ip-1-2-3.eu/ips":
                ["1.2.3.4/32", "2001:41d0:2::1/64"],
        })
        try:
            out = discovery.ovhcloud_sd({
                "endpoint": f"http://127.0.0.1:{srv.port}/1.0",
                "service": "dedicated_server"})
            assert out[0][0] == "1.2.3.4:80"
            meta = out[0][1]
            assert meta["__meta_ovhcloud_dedicated_server_datacenter"] \
                == "gra1"
            assert meta["__meta_ovhcloud_dedicated_server_ipv4"] \
                == "1.2.3.4"
            assert meta["__meta_ovhcloud_dedicated_server_"
                        "no_intervention"] == "false"
        finally:
            srv.stop()


class TestYandexcloudSD:
    def test_instances(self):
        srv = _srv({
            "/resource-manager/v1/clouds": {"clouds": [{"id": "c1"}]},
            "/resource-manager/v1/folders": {"folders": [{"id": "f1"}]},
            "/compute/v1/instances": {"instances": [{
                "id": "i1", "name": "web-1", "fqdn": "web-1.internal",
                "status": "RUNNING", "platformId": "standard-v3",
                "labels": {"env": "prod"},
                "resources": {"cores": "2", "memory": "4294967296",
                              "coreFraction": "100"},
                "networkInterfaces": [{
                    "primaryV4Address": {
                        "address": "10.128.0.5",
                        "oneToOneNat": {"address": "84.201.1.2"},
                        "dnsRecords": [{"fqdn": "web-1.ru-central1"}]}}],
            }]},
        })
        try:
            out = discovery.yandexcloud_sd({
                "api_endpoint": f"http://127.0.0.1:{srv.port}",
                "iam_token": "tk", "port": 9100})
            assert out[0][0] == "10.128.0.5:9100"
            meta = out[0][1]
            assert meta["__meta_yandexcloud_folder_id"] == "f1"
            assert meta["__meta_yandexcloud_instance_label_env"] == "prod"
            assert meta["__meta_yandexcloud_instance_private_ip_0"] \
                == "10.128.0.5"
            assert meta["__meta_yandexcloud_instance_public_ip_0"] \
                == "84.201.1.2"
            # prefer_public_ip switches the target address
            out2 = discovery.yandexcloud_sd({
                "api_endpoint": f"http://127.0.0.1:{srv.port}",
                "iam_token": "tk", "prefer_public_ip": True})
            assert out2[0][0] == "84.201.1.2:80"
        finally:
            srv.stop()


class TestKumaSD:
    def test_monitoring_assignments(self):
        from victoriametrics_tpu.httpapi.server import HTTPServer, Response
        seen = []
        srv = HTTPServer("127.0.0.1", 0)

        def h(r):
            import json as _j
            seen.append(_j.loads(r.body))
            return Response.json({
                "version_info": "v1",
                "resources": [{
                    "mesh": "default", "service": "backend",
                    "labels": {"team": "core"},
                    "targets": [{
                        "name": "backend-01", "address": "10.7.0.2:5670",
                        "scheme": "http", "metrics_path": "/metrics",
                        "labels": {"kuma.io/protocol": "http"}}]}],
                "nonce": "n1"})
        srv.route("/v3/discovery:monitoringassignments", h)
        srv.start()
        try:
            out = discovery.kuma_sd(
                {"server": f"127.0.0.1:{srv.port}"})
            assert seen[0]["type_url"].endswith("MonitoringAssignment")
            assert seen[0]["version_info"] == ""
            assert out[0][0] == "10.7.0.2:5670"
            meta = out[0][1]
            assert meta["__meta_kuma_dataplane"] == "backend-01"
            assert meta["__meta_kuma_mesh"] == "default"
            assert meta["__meta_kuma_service"] == "backend"
            assert meta["__meta_kuma_label_team"] == "core"
            assert meta["__meta_kuma_label_kuma_io_protocol"] == "http"
            assert meta["__metrics_path__"] == "/metrics"
        finally:
            srv.stop()
