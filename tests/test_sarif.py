"""SARIF 2.1.0 emitter tests (devtools/sarif.py).

``to_sarif`` output must validate against the vendored structural
subset of the official SARIF 2.1.0 schema
(devtools/sarif_schema_2.1.0.json) — so CI/code-scanning upload
endpoints that consume SARIF accept vmt-lint's logs — and the lint CLI
``--format=sarif`` path must emit exactly one parseable log on stdout
with the same exit-code contract as text mode."""

import json
import os

import jsonschema
import pytest

from victoriametrics_tpu.devtools import sarif
from victoriametrics_tpu.devtools.lint import Finding

_SCHEMA_PATH = os.path.join(
    os.path.dirname(os.path.abspath(sarif.__file__)),
    "sarif_schema_2.1.0.json")


@pytest.fixture(scope="module")
def schema():
    with open(_SCHEMA_PATH, encoding="utf-8") as fh:
        return json.load(fh)


def _validate(log, schema):
    jsonschema.validate(log, schema,
                        format_checker=jsonschema.FormatChecker())


def test_empty_log_validates(schema):
    log = sarif.to_sarif([])
    _validate(log, schema)
    assert log["version"] == "2.1.0"
    assert log["runs"][0]["results"] == []
    assert log["runs"][0]["tool"]["driver"]["name"] == "vmt-lint"


def test_findings_log_validates_with_rule_catalog(schema):
    findings = [
        Finding("victoriametrics_tpu/query/engine.py", 42, "VMT015",
                "field x has no consistent guard"),
        Finding("victoriametrics_tpu/parallel/rpc.py", 7, "VMT016",
                "AppError escapes to the rpc boundary"),
        Finding("victoriametrics_tpu/utils/fs.py", 0, "VMT001",
                "zero line anchors clamp to 1"),
    ]
    log = sarif.to_sarif(findings, {"VMT015": "lockset inference",
                                    "VMT016": "exception-escape audit"})
    _validate(log, schema)
    run = log["runs"][0]
    rules = run["tool"]["driver"]["rules"]
    rule_ids = [r["id"] for r in rules]
    # every emitted result's ruleIndex points back at its catalog row
    for res in run["results"]:
        assert rule_ids[res["ruleIndex"]] == res["ruleId"]
        assert res["level"] == "error"
        region = res["locations"][0]["physicalLocation"]["region"]
        assert region["startLine"] >= 1   # SARIF forbids line 0
        art = res["locations"][0]["physicalLocation"]["artifactLocation"]
        assert art["uriBaseId"] == "SRCROOT"
    # summaries attached where provided
    by_id = {r["id"]: r for r in rules}
    assert by_id["VMT015"]["shortDescription"]["text"] == \
        "lockset inference"


def test_mutated_log_fails_validation(schema):
    """The vendored schema is a real gate, not a rubber stamp: break a
    required property and validation must reject the log."""
    log = sarif.to_sarif(
        [Finding("a.py", 1, "VMT001", "m")])
    log["version"] = "9.9.9"
    with pytest.raises(jsonschema.ValidationError):
        _validate(log, schema)
    log = sarif.to_sarif([Finding("a.py", 1, "VMT001", "m")])
    del log["runs"][0]["results"][0]["message"]
    with pytest.raises(jsonschema.ValidationError):
        _validate(log, schema)


def test_lint_cli_sarif_output_validates(schema, capsys):
    """``lint --format=sarif`` on one clean file: exit 0, stdout is one
    valid SARIF log, diagnostics stay off stdout."""
    from victoriametrics_tpu.devtools import lint
    rc = lint.main(["--format=sarif", "--no-program-passes",
                    "victoriametrics_tpu/devtools/sarif.py"])
    out = capsys.readouterr().out
    log = json.loads(out)
    _validate(log, schema)
    assert rc == 0
    assert log["runs"][0]["results"] == []


def test_errorflow_cli_sarif_output_validates(schema, capsys):
    """The standalone pass CLIs share the emitter: errorflow
    --format=sarif over a tiny fixture-free path emits a valid log."""
    from victoriametrics_tpu.devtools import errorflow
    rc = errorflow.main(["--format=sarif",
                         "victoriametrics_tpu/devtools/sarif.py"])
    out = capsys.readouterr().out
    log = json.loads(out)
    _validate(log, schema)
    assert rc == 0
