"""Regression guard for O(new samples) steady-state serving: a rolling
dashboard refresh through the cached range executor must FETCH only the
uncovered suffix, not the full window.  Asserted via the per-query
sample accumulator (EvalConfig.samples_scanned, the seriesFetched
analog) with the vm_fetch_phase counters as a sanity cross-check — a
future change silently re-introducing full-window refetch fails here
loudly.  Tier-1 safe: pure-Python storage paths, no native lib or
device required."""

import time

import numpy as np
import pytest

from victoriametrics_tpu.httpapi.prometheus_api import PrometheusAPI
from victoriametrics_tpu.query import rollup_result_cache as rrc
from victoriametrics_tpu.query.exec import exec_query
from victoriametrics_tpu.query.types import EvalConfig
from victoriametrics_tpu.storage.storage import Storage
from victoriametrics_tpu.utils import metrics as metricslib

STEP = 60_000
SCRAPE = 15_000
NS = 8
NN = 1500          # 6.25h @ 15s -> suffix fetch is ~1% of a cold window
Q = "sum by (g)(rate(guard[2m]))"


@pytest.fixture()
def store(tmp_path):
    s = Storage(str(tmp_path / "s"))
    now = int(time.time() * 1000)
    t0 = (now - (NN - 1) * SCRAPE) // STEP * STEP
    rng = np.random.default_rng(3)
    rows = []
    for i in range(NS):
        vals = np.cumsum(rng.integers(0, 30, NN)).astype(np.float64)
        rows.extend((({"__name__": "guard", "i": str(i),
                       "g": f"g{i % 2}"}, t0 + j * SCRAPE, float(vals[j]))
                     for j in range(NN)))
    s.add_rows(rows)
    s.force_flush()
    end0 = t0 + ((NN - 1) * SCRAPE // STEP + 1) * STEP
    yield s, end0
    s.close()


def test_refresh_fetches_only_the_suffix(store):
    s, end = store
    rrc.GLOBAL.reset()
    api = PrometheusAPI(s)
    dur = (NN - 1) * SCRAPE // STEP * STEP - 10 * STEP
    start = end - dur

    # cold reference: what one full-window evaluation scans
    cold_ec = EvalConfig(start=start, end=end, step=STEP, storage=s,
                         disable_cache=True)
    exec_query(cold_ec, Q)
    cold_samples = cold_ec.samples_scanned
    assert cold_samples > 0

    # warm the cache, then roll the window with live ingest
    api._exec_range_cached(EvalConfig(start=start, end=end, step=STEP,
                                      storage=s), Q,
                           int(time.time() * 1000))
    inplace0 = metricslib.REGISTRY.counter(
        "vm_rollup_cache_inplace_total").get()
    fetch_phase = metricslib.REGISTRY.float_counter(
        'vm_fetch_phase_seconds_total{phase="index_search"}')
    phase0 = fetch_phase.get()
    for r in range(3):
        end += STEP
        start = end - dur
        s.add_rows([({"__name__": "guard", "i": str(i), "g": f"g{i % 2}"},
                     end - STEP + (k + 1) * SCRAPE, float(10_000 + r + k))
                    for i in range(NS) for k in range(4)])
        ec = EvalConfig(start=start, end=end, step=STEP, storage=s)
        served = api._exec_range_cached(ec, Q, int(time.time() * 1000))
        assert len(served) == 2
        # THE guard: a refresh must scan O(suffix), not the window.
        # The suffix fetch covers [new_start - window - lookback_delta,
        # end] (~8min here) vs the ~6h cold window -> well under 5%.
        assert ec.samples_scanned < 0.05 * cold_samples, (
            f"refresh {r} fetched {ec.samples_scanned} samples "
            f"(cold window = {cold_samples}): steady-state serving has "
            f"regressed to full-window refetch")
    # sanity cross-checks: the refreshes really went through the fetch
    # path (phase counters ticked) and extended the cache in place
    assert fetch_phase.get() >= phase0
    assert metricslib.REGISTRY.counter(
        "vm_rollup_cache_inplace_total").get() > inplace0
