"""Query engine tests — modeled on the reference's exec_test.go style:
queries against a seeded storage, hand-computed expectations."""

import numpy as np
import pytest

from victoriametrics_tpu.query.eval import QueryError
from victoriametrics_tpu.query.exec import exec_query, exec_instant
from victoriametrics_tpu.query.types import EvalConfig
from victoriametrics_tpu.storage.storage import Storage

T0 = 1_753_700_000_000
STEP = 60_000
END = T0 + 20 * STEP


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    s = Storage(str(tmp_path_factory.mktemp("qe") / "s"))
    rows = []
    # counters: http_requests_total{job, instance} at 15s, rate 10/s and 20/s
    for j in range(121):
        ts = T0 - 600_000 + j * 15_000
        rows.append(({"__name__": "http_requests_total", "job": "api",
                      "instance": "h1"}, ts, 150.0 * j))
        rows.append(({"__name__": "http_requests_total", "job": "api",
                      "instance": "h2"}, ts, 300.0 * j))
        rows.append(({"__name__": "http_requests_total", "job": "web",
                      "instance": "h3"}, ts, 600.0 * j))
    # gauge
    for j in range(121):
        ts = T0 - 600_000 + j * 15_000
        rows.append(({"__name__": "mem_bytes", "instance": "h1"}, ts,
                     float(100 + (j % 10))))
        rows.append(({"__name__": "mem_bytes", "instance": "h2"}, ts,
                     float(200 + (j % 5))))
    # histogram buckets (cumulative): 60% <=0.1, 90% <=1, 100% <=+Inf
    for j in range(121):
        ts = T0 - 600_000 + j * 15_000
        for le, frac in (("0.1", 0.6), ("1", 0.9), ("+Inf", 1.0)):
            rows.append(({"__name__": "latency_bucket", "le": le},
                         ts, 100.0 * j * frac))
    s.add_rows(rows)
    s.force_flush()
    yield s
    s.close()


@pytest.fixture()
def ec(store):
    return EvalConfig(start=T0, end=END, step=STEP, storage=store)


def names(rows):
    return [r.metric_name.to_dict() for r in rows]


class TestSelectors:
    def test_plain_selector_last_value(self, ec):
        rows = exec_query(ec, "mem_bytes")
        assert len(rows) == 2
        assert rows[0].metric_name.to_dict()["__name__"] == "mem_bytes"
        assert not np.isnan(rows[0].values).any()

    def test_filtered_selector(self, ec):
        rows = exec_query(ec, 'http_requests_total{job="api"}')
        assert len(rows) == 2

    def test_regex_selector(self, ec):
        rows = exec_query(ec, '{__name__=~"http_.*", instance=~"h1|h3"}')
        assert len(rows) == 2

    def test_missing_metric_empty(self, ec):
        assert exec_query(ec, "nope_metric") == []


class TestRollups:
    def test_rate_counter(self, ec):
        rows = exec_query(ec, "rate(http_requests_total[5m])")
        assert len(rows) == 3
        by_inst = {r.metric_name.get_label(b"instance"): r for r in rows}
        np.testing.assert_allclose(by_inst[b"h1"].values, 10.0, rtol=1e-9)
        np.testing.assert_allclose(by_inst[b"h2"].values, 20.0, rtol=1e-9)
        np.testing.assert_allclose(by_inst[b"h3"].values, 40.0, rtol=1e-9)
        # rate() drops the metric name
        assert rows[0].metric_name.metric_group == b""

    def test_increase(self, ec):
        rows = exec_query(ec, "increase(http_requests_total[5m])")
        by_inst = {r.metric_name.get_label(b"instance"): r for r in rows}
        np.testing.assert_allclose(by_inst[b"h1"].values, 3000.0, rtol=1e-9)

    def test_avg_over_time_keeps_name(self, ec):
        rows = exec_query(ec, "avg_over_time(mem_bytes[5m])")
        assert rows[0].metric_name.metric_group == b"mem_bytes"

    def test_window_defaults_to_step(self, ec):
        rows = exec_query(ec, "count_over_time(mem_bytes[1m])")
        np.testing.assert_allclose(rows[0].values, 4.0)

    def test_offset(self, ec):
        a = exec_query(ec, "http_requests_total offset 5m")
        b = exec_query(ec, "http_requests_total")
        # counter grows 150 per 15s on h1 -> offset shifts by 5m = 3000
        ai = [r for r in a if r.metric_name.get_label(b"instance") == b"h1"][0]
        bi = [r for r in b if r.metric_name.get_label(b"instance") == b"h1"][0]
        np.testing.assert_allclose(bi.values - ai.values, 3000.0)

    def test_quantile_over_time(self, ec):
        rows = exec_query(ec, "quantile_over_time(1, mem_bytes[5m])")
        by_inst = {r.metric_name.get_label(b"instance"): r for r in rows}
        np.testing.assert_allclose(by_inst[b"h1"].values, 109.0)

    def test_subquery(self, ec):
        rows = exec_query(ec, "max_over_time(rate(http_requests_total[5m])[10m:1m])")
        by_inst = {r.metric_name.get_label(b"instance"): r for r in rows}
        np.testing.assert_allclose(by_inst[b"h1"].values, 10.0, rtol=1e-9)

    def test_at_modifier(self, ec):
        rows = exec_query(ec, f"mem_bytes @ {(T0 + 5 * STEP) // 1000}")
        # broadcast: constant across grid
        for r in rows:
            assert (r.values == r.values[0]).all()

    def test_predict_linear(self, ec):
        rows = exec_query(ec, "predict_linear(http_requests_total{instance=\"h1\"}[5m], 60)")
        # slope 10/s -> prediction at te+60 follows the line
        assert rows[0].values.size == 21
        d = np.diff(rows[0].values)
        np.testing.assert_allclose(d, 600.0, rtol=1e-6)


class TestAggregates:
    def test_sum_by_job(self, ec):
        rows = exec_query(ec, "sum by (job) (rate(http_requests_total[5m]))")
        assert names(rows) == [{"job": "api"}, {"job": "web"}]
        np.testing.assert_allclose(rows[0].values, 30.0, rtol=1e-9)
        np.testing.assert_allclose(rows[1].values, 40.0, rtol=1e-9)

    def test_sum_without(self, ec):
        rows = exec_query(ec, "sum without (instance) (rate(http_requests_total[5m]))")
        assert names(rows) == [{"job": "api"}, {"job": "web"}]

    def test_global_sum(self, ec):
        rows = exec_query(ec, "sum(rate(http_requests_total[5m]))")
        assert len(rows) == 1 and rows[0].metric_name.to_dict() == {}
        np.testing.assert_allclose(rows[0].values, 70.0, rtol=1e-9)

    def test_avg_min_max_count(self, ec):
        for q, want in [("avg(mem_bytes)", None), ("count(mem_bytes)", 2.0),
                        ("min(mem_bytes)", None), ("max(mem_bytes)", None)]:
            rows = exec_query(ec, q)
            assert len(rows) == 1
            if want is not None:
                np.testing.assert_allclose(rows[0].values, want)

    def test_topk(self, ec):
        rows = exec_query(ec, "topk(1, rate(http_requests_total[5m]))")
        assert len(rows) == 1
        assert rows[0].metric_name.get_label(b"instance") == b"h3"

    def test_topk_avg(self, ec):
        rows = exec_query(ec, "topk_avg(2, rate(http_requests_total[5m]))")
        insts = {r.metric_name.get_label(b"instance") for r in rows}
        assert insts == {b"h2", b"h3"}

    def test_quantile_aggr(self, ec):
        rows = exec_query(ec, "quantile(0.5, rate(http_requests_total[5m]))")
        np.testing.assert_allclose(rows[0].values, 20.0, rtol=1e-9)

    def test_count_values(self, ec):
        rows = exec_instant(ec, 'count_values("v", floor(mem_bytes/100))',
                            T0 + 10 * STEP)
        d = {r.metric_name.get_label(b"v"): r.values[0] for r in rows}
        assert d == {b"1": 1.0, b"2": 1.0}

    def test_limit(self, ec):
        rows = exec_query(ec, "sum(rate(http_requests_total[5m])) by (instance) limit 2")
        assert len(rows) == 2


class TestBinaryOps:
    def test_vector_scalar(self, ec):
        rows = exec_query(ec, "mem_bytes * 2")
        by_inst = {r.metric_name.get_label(b"instance"): r for r in rows}
        assert (by_inst[b"h1"].values >= 200).all()
        assert rows[0].metric_name.metric_group == b""

    def test_comparison_filters(self, ec):
        rows = exec_query(ec, "mem_bytes > 150")
        assert len(rows) == 1
        assert rows[0].metric_name.get_label(b"instance") == b"h2"
        # name kept for filtering comparisons
        assert rows[0].metric_name.metric_group == b"mem_bytes"

    def test_comparison_bool(self, ec):
        rows = exec_query(ec, "mem_bytes > bool 150")
        assert len(rows) == 2
        by_inst = {r.metric_name.get_label(b"instance"): r for r in rows}
        np.testing.assert_allclose(by_inst[b"h1"].values, 0.0)
        np.testing.assert_allclose(by_inst[b"h2"].values, 1.0)

    def test_vector_vector_matching(self, ec):
        rows = exec_query(ec, "rate(http_requests_total[5m]) "
                              "/ on(instance) mem_bytes")
        assert len(rows) == 0 or len(rows) == 2  # h1, h2 match; h3 has no mem
        rows = exec_query(
            ec, 'rate(http_requests_total{instance=~"h1|h2"}[5m]) '
                '/ on(instance) mem_bytes')
        assert len(rows) == 2

    def test_and_or_unless(self, ec):
        rows = exec_query(ec, 'mem_bytes and on(instance) '
                              'http_requests_total{instance="h1"}')
        assert len(rows) == 1
        rows = exec_query(ec, 'mem_bytes unless on(instance) '
                              'http_requests_total{instance="h1"}')
        assert len(rows) == 1
        assert rows[0].metric_name.get_label(b"instance") == b"h2"

    def test_or_union(self, ec):
        rows = exec_query(ec, 'mem_bytes{instance="h1"} or mem_bytes{instance="h2"}')
        assert len(rows) == 2

    def test_default(self, ec):
        rows = exec_query(ec, "nope_metric default 7")
        assert rows == []  # no left series at all
        rows = exec_query(ec, "(mem_bytes > 150) default 0")
        by_inst = {r.metric_name.get_label(b"instance"): r for r in rows}
        np.testing.assert_allclose(by_inst[b"h1"].values, 0.0)

    def test_scalar_scalar(self, ec):
        rows = exec_query(ec, "2 + 3 * 4")
        np.testing.assert_allclose(rows[0].values, 14.0)

    def test_duration_scalar(self, ec):
        rows = exec_query(ec, "5m / 60")
        np.testing.assert_allclose(rows[0].values, 5.0)

    def test_group_left(self, ec):
        rows = exec_query(
            ec, "rate(http_requests_total[5m]) * on(instance) group_left() "
                "(mem_bytes / mem_bytes)")
        assert len(rows) == 2


class TestTransforms:
    def test_math(self, ec):
        rows = exec_query(ec, "abs(-1 * mem_bytes)")
        assert (rows[0].values > 0).all()

    def test_histogram_quantile(self, ec):
        rows = exec_query(
            ec, "histogram_quantile(0.5, rate(latency_bucket[5m]))")
        assert len(rows) == 1
        # 50th pct inside first bucket [0, 0.1]: 0.5/0.6 * 0.1
        np.testing.assert_allclose(rows[0].values, 0.5 / 0.6 * 0.1, rtol=1e-6)

    def test_histogram_quantile_99(self, ec):
        rows = exec_query(
            ec, "histogram_quantile(0.99, rate(latency_bucket[5m]))")
        # between 0.9 and 1.0 cumfrac: in bucket (0.1, 1]
        v = rows[0].values[0]
        assert 0.1 < v <= 1.0

    def test_label_set_and_del(self, ec):
        rows = exec_query(ec, 'label_set(mem_bytes, "dc", "eu")')
        assert rows[0].metric_name.get_label(b"dc") == b"eu"
        rows = exec_query(ec, 'label_del(mem_bytes, "instance")')
        assert rows[0].metric_name.get_label(b"instance") is None

    def test_label_replace(self, ec):
        rows = exec_query(ec, 'label_replace(mem_bytes, "host", "$1", '
                              '"instance", "(h\\\\d+)")')
        hosts = sorted(r.metric_name.get_label(b"host") for r in rows)
        assert hosts == [b"h1", b"h2"]

    def test_label_join(self, ec):
        rows = exec_query(ec, 'label_join(mem_bytes, "ij", "-", "instance", "instance")')
        assert rows[0].metric_name.get_label(b"ij") in (b"h1-h1", b"h2-h2")

    def test_absent(self, ec):
        rows = exec_query(ec, "absent(nope_metric)")
        np.testing.assert_allclose(rows[0].values, 1.0)
        assert exec_query(ec, "absent(mem_bytes)") == []

    def test_clamp(self, ec):
        rows = exec_query(ec, "clamp(mem_bytes, 150, 202)")
        m = np.vstack([r.values for r in rows])
        assert m.min() >= 150 and m.max() <= 202

    def test_time_and_timestamp(self, ec):
        rows = exec_query(ec, "time()")
        np.testing.assert_allclose(rows[0].values[0], T0 / 1e3)
        rows = exec_query(ec, "timestamp(mem_bytes)")
        assert rows[0].values[-1] <= END / 1e3

    def test_scalar_vector_roundtrip(self, ec):
        rows = exec_query(ec, "vector(scalar(sum(mem_bytes)))")
        assert len(rows) == 1

    def test_sort_and_running(self, ec):
        rows = exec_query(ec, "sort_desc(mem_bytes)")
        assert rows[0].metric_name.get_label(b"instance") == b"h2"
        rows = exec_query(ec, "running_max(mem_bytes)")
        for r in rows:
            assert (np.diff(r.values) >= 0).all()

    def test_interpolate_and_keep_last(self, ec):
        rows = exec_query(ec, "interpolate(mem_bytes)")
        assert not np.isnan(rows[0].values).any()

    def test_round_nearest(self, ec):
        rows = exec_query(ec, "round(mem_bytes, 100)")
        assert set(np.unique(rows[0].values)) <= {100.0, 200.0}

    def test_union(self, ec):
        rows = exec_query(ec, "union(mem_bytes, rate(http_requests_total[5m]))")
        assert len(rows) == 5

    def test_day_funcs(self, ec):
        rows = exec_query(ec, "hour()")
        assert 0 <= rows[0].values[0] <= 23


class TestErrors:
    def test_unknown_function(self, ec):
        with pytest.raises(QueryError):
            exec_query(ec, "frobnicate(mem_bytes)")

    def test_unknown_aggregate_parses_as_func(self, ec):
        with pytest.raises(QueryError):
            exec_query(ec, "supersum(mem_bytes)")

    def test_instant(self, ec):
        rows = exec_instant(ec, "sum(mem_bytes)", T0 + 5 * STEP)
        assert len(rows) == 1 and rows[0].values.size == 1


class TestStaleNaNHandling:
    """reference eval.go:2081 dropStaleNaNs — staleness markers must not
    poison non-default rollup windows."""

    @pytest.fixture()
    def stale_store(self, tmp_path):
        from victoriametrics_tpu.ops import decimal as dec
        s = Storage(str(tmp_path / "stale"))
        rows = []
        for j in range(121):
            ts = T0 - 600_000 + j * 15_000
            rows.append(({"__name__": "ctr"}, ts, 10.0 * j))
        # staleness marker mid-stream (target restart)
        rows.append(({"__name__": "ctr"}, T0 + 5 * STEP + 1000,
                     dec.STALE_NAN))
        s.add_rows(rows)
        s.force_flush()
        yield s
        s.close()

    def test_rate_ignores_marker(self, stale_store):
        ec = EvalConfig(start=T0, end=END, step=STEP, storage=stale_store)
        rows = exec_query(ec, "rate(ctr[5m])")
        assert len(rows) == 1
        assert not np.isnan(rows[0].values).any()
        assert np.allclose(rows[0].values, 10.0 / 15.0, rtol=1e-6)

    def test_sum_over_time_ignores_marker(self, stale_store):
        ec = EvalConfig(start=T0, end=END, step=STEP, storage=stale_store)
        for fn in ("sum_over_time", "avg_over_time"):
            rows = exec_query(ec, f"{fn}(ctr[5m])")
            assert not np.isnan(rows[0].values).any(), fn

    def test_stale_samples_over_time_counts_marker(self, stale_store):
        ec = EvalConfig(start=T0, end=END, step=STEP, storage=stale_store)
        rows = exec_query(ec, "stale_samples_over_time(ctr[5m])")
        assert rows[0].values.max() == 1.0


class TestBinopLabelStripping:
    def test_ignoring_strips_labels_one_to_one(self, ec):
        # a / ignoring(instance) b must drop `instance` from the result
        rows = exec_query(
            ec, 'mem_bytes{instance="h1"} / ignoring(instance) '
                'mem_bytes{instance="h1"}')
        assert len(rows) == 1
        for r in rows:
            assert "instance" not in r.metric_name.to_dict()

    def test_on_keeps_only_on_labels(self, ec):
        rows = exec_query(ec, 'mem_bytes / on(instance) mem_bytes')
        for r in rows:
            assert set(r.metric_name.to_dict()) <= {"instance"}


class TestQueryLimits:
    """-search.max* family + memory admission (eval.go:1776-1885)."""

    @pytest.fixture()
    def lim_store(self, tmp_path):
        s = Storage(str(tmp_path / "lim"))
        rows = []
        for i in range(50):
            for j in range(30):
                rows.append(({"__name__": "lm", "i": str(i)},
                             T0 - 600_000 + j * 15_000, float(j)))
        s.add_rows(rows)
        yield s
        s.close()

    def test_max_samples_per_query(self, lim_store):
        from victoriametrics_tpu.query.limits import QueryLimitError
        ec = EvalConfig(start=T0, end=END, step=STEP, storage=lim_store,
                        max_samples_per_query=100)
        with pytest.raises(QueryLimitError, match="maxSamplesPerQuery"):
            exec_query(ec, "rate(lm[5m])")

    def test_max_series(self, lim_store):
        from victoriametrics_tpu.query.limits import QueryLimitError
        ec = EvalConfig(start=T0, end=END, step=STEP, storage=lim_store,
                        max_series=10)
        with pytest.raises(QueryLimitError, match="maxUniqueTimeseries"):
            exec_query(ec, "rate(lm[5m])")

    def test_max_memory_per_query(self, lim_store):
        from victoriametrics_tpu.query.limits import QueryLimitError
        ec = EvalConfig(start=T0, end=END, step=STEP, storage=lim_store,
                        max_memory_per_query=1000)
        with pytest.raises(QueryLimitError, match="maxMemoryPerQuery"):
            exec_query(ec, "rate(lm[5m])")

    def test_deadline(self, lim_store):
        import time as _t
        from victoriametrics_tpu.query.limits import QueryLimitError
        ec = EvalConfig(start=T0, end=END, step=STEP, storage=lim_store,
                        deadline=_t.monotonic() - 1)
        with pytest.raises(QueryLimitError, match="maxQueryDuration"):
            exec_query(ec, "rate(lm[5m])")

    def test_memory_admission_releases(self, lim_store):
        from victoriametrics_tpu.query.limits import rollup_memory_limiter
        lim = rollup_memory_limiter()
        before = lim.usage
        ec = EvalConfig(start=T0, end=END, step=STEP, storage=lim_store)
        exec_query(ec, "rate(lm[5m])")
        assert lim.usage == before

    def test_samples_accumulate_across_selectors(self, lim_store):
        from victoriametrics_tpu.query.limits import QueryLimitError
        # each selector scans ~1500; the cap of 2000 only trips summed
        ec = EvalConfig(start=T0, end=END, step=STEP, storage=lim_store,
                        max_samples_per_query=2000)
        with pytest.raises(QueryLimitError):
            exec_query(ec, "rate(lm[5m]) + avg_over_time(lm[5m])")

    def test_fused_fallback_does_not_double_count(self, lim_store):
        # fused path fetches then declines (min_series) -> host re-fetch
        # must not double-count toward maxSamplesPerQuery
        from victoriametrics_tpu.query.tpu_engine import TPUEngine
        ec = EvalConfig(start=T0, end=END, step=STEP, storage=lim_store,
                        max_samples_per_query=2000,
                        tpu=TPUEngine(min_series=1000))
        rows = exec_query(ec, "sum(rate(lm[5m]))")  # ~1500 samples scanned
        assert len(rows) == 1

    def test_subquery_shares_accumulator(self, lim_store):
        from victoriametrics_tpu.query.limits import QueryLimitError
        ec = EvalConfig(start=T0, end=END, step=STEP, storage=lim_store,
                        max_samples_per_query=2500)
        # inner subquery selector + outer selector together exceed the cap
        with pytest.raises(QueryLimitError):
            exec_query(ec, "max_over_time(rate(lm[1m])[5m:30s]) + rate(lm[5m])")


class TestEvalRollupCache:
    def test_repeated_eval_hits_cache(self, tmp_path):
        import time as _t
        from victoriametrics_tpu.query.rollup_result_cache import GLOBAL
        s = Storage(str(tmp_path / "erc"))
        now = int(_t.time() * 1000)
        rows = [({"__name__": "erc", "i": str(i)},
                 now - 3600_000 + j * 60_000, float(j))
                for i in range(20) for j in range(50)]
        s.add_rows(rows)
        start = now - 3000_000
        start -= start % 60_000
        ec_kw = dict(start=start, end=start + 1800_000, step=60_000,
                     storage=s)
        h0 = GLOBAL.hits
        r1 = exec_query(EvalConfig(**ec_kw), "avg_over_time(erc[5m])")
        r2 = exec_query(EvalConfig(**ec_kw), "avg_over_time(erc[5m])")
        assert GLOBAL.hits > h0
        m1 = {ts.metric_name.marshal(): ts.values for ts in r1}
        m2 = {ts.metric_name.marshal(): ts.values for ts in r2}
        assert set(m1) == set(m2) and len(m1) == 20
        for k in m1:
            np.testing.assert_allclose(m1[k], m2[k], equal_nan=True)
        # sub-expression reuse across DIFFERENT enclosing queries
        r3 = exec_query(EvalConfig(**ec_kw),
                        "sum(avg_over_time(erc[5m]))")
        assert len(r3) == 1
        # storages don't share cache entries
        s2 = Storage(str(tmp_path / "erc2"))
        assert exec_query(EvalConfig(**{**ec_kw, "storage": s2}),
                          "avg_over_time(erc[5m])") == []
        s2.close()
        s.close()
