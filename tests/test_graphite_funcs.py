"""Graphite render function library tests (reference coverage:
app/vmselect/graphite/eval_test.go exercises the functions.json set; the
cases here are value-checked transcriptions of its common shapes over a
deterministic fixture).

Fixture: servers.web{1,2}.cpu.load = 0..29 step 1/min, and
servers.web1.mem.used = 100..129 (dc=east).
"""

import json
import math

import numpy as np
import pytest

from tests.apptest_helpers import Client

T0 = 1_753_700_000_000


@pytest.fixture(scope="module")
def app(tmp_path_factory):
    from victoriametrics_tpu.apps.vmsingle import build, parse_flags
    tmp_path = tmp_path_factory.mktemp("gfn")
    args = parse_flags([f"-storageDataPath={tmp_path}/data",
                        "-httpListenAddr=127.0.0.1:0"])
    storage, srv, api = build(args)
    srv.start()
    rows = []
    for host in ("web1", "web2"):
        for j in range(30):
            rows.append(({"__name__": f"servers.{host}.cpu.load"},
                         T0 + j * 60_000, float(j)))
    for j in range(30):
        rows.append(({"__name__": "servers.web1.mem.used", "dc": "east"},
                     T0 + j * 60_000, 100.0 + j))
    storage.add_rows(rows)
    yield Client(srv.port)
    srv.stop()
    storage.close()


def render(app, target, **kw):
    params = {"target": target, "from": str((T0 - 60_000) // 1000),
              "until": str((T0 + 29 * 60_000) // 1000),
              "format": "json", **kw}
    code, body = app.get("/render", **params)
    assert code == 200, body
    return json.loads(body)


def vals(series):
    return [p[0] for p in series["datapoints"] if p[0] is not None]


class TestCombiners:
    def test_diff_series(self, app):
        out = render(app, "diffSeries(servers.web1.mem.used,"
                          "servers.web1.cpu.load)")
        assert vals(out[0])[:3] == [100.0, 100.0, 100.0]

    def test_multiply_series(self, app):
        out = render(app, "multiplySeries(servers.*.cpu.load)")
        assert vals(out[0])[:4] == [0.0, 1.0, 4.0, 9.0]

    def test_range_count_stddev(self, app):
        assert vals(render(app, "rangeOfSeries(servers.*.cpu.load)")[0])[:2] \
            == [0.0, 0.0]
        assert vals(render(app, "countSeries(servers.*.cpu.load)")[0])[:2] \
            == [2.0, 2.0]
        assert vals(render(app, "stddevSeries(servers.*.cpu.load)")[0])[:2] \
            == [0.0, 0.0]

    def test_aggregate_generic(self, app):
        out = render(app, 'aggregate(servers.*.cpu.load, "max")')
        assert vals(out[0])[:3] == [0.0, 1.0, 2.0]

    def test_percentile_of_series(self, app):
        out = render(app, "percentileOfSeries(servers.*.cpu.load, 50)")
        assert vals(out[0])[:3] == [0.0, 1.0, 2.0]

    def test_group_by_tags(self, app):
        out = render(app, 'groupByTags(seriesByTag(\'dc=east\'), "sum", '
                          '"dc")')
        assert len(out) == 1 and out[0]["tags"].get("dc") == "east"

    def test_pow_series_lists(self, app):
        out = render(app, "sumSeriesLists(servers.web1.cpu.load,"
                          "servers.web2.cpu.load)")
        assert vals(out[0])[:3] == [0.0, 2.0, 4.0]


class TestTransforms:
    def test_invert_log_sqrt(self, app):
        v = vals(render(app, "invert(servers.web1.mem.used)")[0])
        assert abs(v[0] - 0.01) < 1e-12
        v = vals(render(app, "squareRoot(servers.web1.mem.used)")[0])
        assert abs(v[0] - 10.0) < 1e-12
        v = vals(render(app, "logarithm(servers.web1.mem.used)")[0])
        assert abs(v[0] - 2.0) < 1e-12

    def test_offset_to_zero(self, app):
        v = vals(render(app, "offsetToZero(servers.web1.mem.used)")[0])
        assert v[:3] == [0.0, 1.0, 2.0]

    def test_transform_null_is_non_null(self, app):
        out = render(app, "transformNull(servers.web1.cpu.load, -1)")
        pts = [p[0] for p in out[0]["datapoints"]]
        assert -1 in pts  # the leading empty bucket became -1
        out = render(app, "isNonNull(servers.web1.cpu.load)")
        assert set(vals(out[0])) <= {0.0, 1.0}

    def test_integral(self, app):
        v = vals(render(app, "integral(servers.web1.cpu.load)")[0])
        assert v[:4] == [0.0, 1.0, 3.0, 6.0]

    def test_derivative_round(self, app):
        v = vals(render(app, "derivative(servers.web1.cpu.load)")[0])
        assert all(x == 1.0 for x in v)
        v = vals(render(app, "round(scale(servers.web1.cpu.load, 0.3))")[0])
        assert v[:4] == [0.0, 0.0, 1.0, 1.0]

    def test_time_shift(self, app):
        out = render(app, 'timeShift(servers.web1.cpu.load, "5min")')
        v = vals(out[0])
        # shifted 5 minutes back: values lag by 5
        assert v[0] == 0.0 and len(v) <= 26
        assert out[0]["target"].startswith("timeShift(")

    def test_moving_average(self, app):
        out = render(app, "movingAverage(servers.web1.cpu.load, 3)")
        v = vals(out[0])
        assert v[:4] == [0.0, 0.5, 1.0, 2.0]

    def test_moving_sum_median(self, app):
        v = vals(render(app, "movingSum(servers.web1.cpu.load, 2)")[0])
        assert v[:4] == [0.0, 1.0, 3.0, 5.0]
        v = vals(render(app, "movingMedian(servers.web1.cpu.load, 3)")[0])
        assert v[2:5] == [1.0, 2.0, 3.0]

    def test_ema(self, app):
        v = vals(render(app,
                        "exponentialMovingAverage(servers.web1.cpu.load, 3)"
                        )[0])
        assert abs(v[0]) < 1e-12 and 0 < v[1] < 1

    def test_stdev_linearreg(self, app):
        v = vals(render(app, "stdev(servers.web1.cpu.load, 3)")[0])
        assert abs(v[2] - np.std([0, 1, 2])) < 1e-9
        v = vals(render(app, "linearRegression(servers.web1.cpu.load)")[0])
        d = np.diff(v)
        assert np.allclose(d, d[0])

    def test_n_percentile(self, app):
        v = vals(render(app, "nPercentile(servers.web1.cpu.load, 100)")[0])
        assert all(x == 29.0 for x in v)


class TestFilters:
    def test_above_below(self, app):
        out = render(app, "maximumAbove(servers.*.*.*, 50)")
        assert {s["target"] for s in out} == {"servers.web1.mem.used"}
        out = render(app, "maximumBelow(servers.*.*.*, 50)")
        assert {s["target"] for s in out} == {"servers.web1.cpu.load",
                                              "servers.web2.cpu.load"}
        out = render(app, "averageAbove(servers.*.*.*, 50)")
        assert len(out) == 1

    def test_highest_lowest(self, app):
        out = render(app, 'highest(servers.*.*.*, 1, "average")')
        assert out[0]["target"] == "servers.web1.mem.used"
        out = render(app, "lowestAverage(servers.*.*.*, 2)")
        assert {s["target"] for s in out} == {"servers.web1.cpu.load",
                                              "servers.web2.cpu.load"}
        out = render(app, "highestCurrent(servers.*.*.*, 1)")
        assert out[0]["target"] == "servers.web1.mem.used"

    def test_remove_value_filters(self, app):
        v = vals(render(app, "removeAboveValue(servers.web1.cpu.load, 5)")[0])
        assert max(v) <= 5
        v = vals(render(app, "removeBelowValue(servers.web1.cpu.load, 5)")[0])
        assert min(v) >= 5

    def test_grep_exclude_unique_limit(self, app):
        out = render(app, 'grep(servers.*.*.*, "mem")')
        assert len(out) == 1
        out = render(app, 'exclude(servers.*.*.*, "mem")')
        assert len(out) == 2
        out = render(app, "limit(servers.*.*.*, 2)")
        assert len(out) == 2
        out = render(app, "unique(group(servers.web1.cpu.load,"
                          "servers.web1.cpu.load))")
        assert len(out) == 1

    def test_filter_series(self, app):
        out = render(app, 'filterSeries(servers.*.*.*, "max", ">", 50)')
        assert {s["target"] for s in out} == {"servers.web1.mem.used"}


class TestSortDivide:
    def test_sort_by_name_total(self, app):
        out = render(app, "sortByName(servers.*.*.*)")
        names = [s["target"] for s in out]
        assert names == sorted(names)
        out = render(app, "sortByTotal(servers.*.*.*)")
        assert out[0]["target"] == "servers.web1.mem.used"

    def test_divide_series(self, app):
        out = render(app, "divideSeries(servers.web1.mem.used,"
                          "servers.web1.mem.used)")
        assert all(x == 1.0 for x in vals(out[0]))

    def test_as_percent(self, app):
        out = render(app, "asPercent(servers.*.cpu.load)")
        v2 = vals(out[1]) if len(out) > 1 else []
        # two equal series: each is 50% where nonzero
        joint = [x for x in vals(out[0])[1:] if x is not None]
        assert all(abs(x - 50.0) < 1e-9 for x in joint)

    def test_weighted_average(self, app):
        out = render(app, "weightedAverage(servers.*.cpu.load,"
                          "servers.*.cpu.load, 1)")
        assert len(out) == 1


class TestSynthetic:
    def test_constant_threshold_time(self, app):
        assert all(x == 4.5 for x in vals(render(app, "constantLine(4.5)")[0]))
        out = render(app, 'threshold(3, "lim")')
        assert out[0]["target"] == "lim"
        v = vals(render(app, "time()")[0])
        assert v[1] - v[0] == 60.0

    def test_fallback(self, app):
        out = render(app, "fallbackSeries(no.such.path,"
                          "servers.web1.cpu.load)")
        assert out and out[0]["target"] == "servers.web1.cpu.load"

    def test_holt_winters(self, app):
        out = render(app, "holtWintersForecast(servers.web1.cpu.load)")
        assert len(out) == 1 and out[0]["target"].startswith("holtWinters")
        out = render(app,
                     "holtWintersConfidenceBands(servers.web1.cpu.load)")
        assert len(out) == 2

    def test_alias_sub(self, app):
        out = render(app,
                     'aliasSub(servers.web1.cpu.load, "web(\\d)", "w\\1")')
        assert out[0]["target"] == "servers.w1.cpu.load"

    def test_substr(self, app):
        out = render(app, "substr(servers.web1.cpu.load, 1, 3)")
        assert out[0]["target"] == "web1.cpu"


class TestIntrospection:
    def test_functions_endpoint(self, app):
        code, body = app.get("/functions")
        assert code == 200
        fns = json.loads(body)
        assert len(fns) >= 140
        for must in ("sumSeries", "movingAverage", "asPercent",
                     "holtWintersForecast", "timeShift", "sortByName",
                     "reduceSeries", "groupByTags"):
            assert must in fns, must


# All 151 function names from the reference's functions.json
# (app/vmselect/graphite/functions.json), vendored so the parity claim
# is enforced without the reference checkout present.
GRAPHITE_FUNCTIONS_JSON = [
 "absolute",
 "add",
 "aggregate",
 "aggregateLine",
 "aggregateSeriesLists",
 "aggregateWithWildcards",
 "alias",
 "aliasByMetric",
 "aliasByNode",
 "aliasByTags",
 "aliasQuery",
 "aliasSub",
 "alpha",
 "applyByNode",
 "areaBetween",
 "asPercent",
 "averageAbove",
 "averageBelow",
 "averageOutsidePercentile",
 "averageSeries",
 "averageSeriesWithWildcards",
 "avg",
 "cactiStyle",
 "changed",
 "color",
 "consolidateBy",
 "constantLine",
 "countSeries",
 "cumulative",
 "currentAbove",
 "currentBelow",
 "dashed",
 "delay",
 "derivative",
 "diffSeries",
 "diffSeriesLists",
 "divideSeries",
 "divideSeriesLists",
 "drawAsInfinite",
 "events",
 "exclude",
 "exp",
 "exponentialMovingAverage",
 "fallbackSeries",
 "filterSeries",
 "grep",
 "group",
 "groupByNode",
 "groupByNodes",
 "groupByTags",
 "highest",
 "highestAverage",
 "highestCurrent",
 "highestMax",
 "hitcount",
 "holtWintersAberration",
 "holtWintersConfidenceArea",
 "holtWintersConfidenceBands",
 "holtWintersForecast",
 "identity",
 "integral",
 "integralByInterval",
 "interpolate",
 "invert",
 "isNonNull",
 "keepLastValue",
 "legendValue",
 "limit",
 "lineWidth",
 "linearRegression",
 "log",
 "logit",
 "lowest",
 "lowestAverage",
 "lowestCurrent",
 "map",
 "mapSeries",
 "maxSeries",
 "maximumAbove",
 "maximumBelow",
 "minMax",
 "minSeries",
 "minimumAbove",
 "minimumBelow",
 "mostDeviant",
 "movingAverage",
 "movingMax",
 "movingMedian",
 "movingMin",
 "movingSum",
 "movingWindow",
 "multiplySeries",
 "multiplySeriesLists",
 "multiplySeriesWithWildcards",
 "nPercentile",
 "nonNegativeDerivative",
 "offset",
 "offsetToZero",
 "pct",
 "perSecond",
 "percentileOfSeries",
 "pow",
 "powSeries",
 "randomWalk",
 "randomWalkFunction",
 "rangeOfSeries",
 "reduce",
 "reduceSeries",
 "removeAbovePercentile",
 "removeAboveValue",
 "removeBelowPercentile",
 "removeBelowValue",
 "removeBetweenPercentile",
 "removeEmptySeries",
 "round",
 "scale",
 "scaleToSeconds",
 "secondYAxis",
 "seriesByTag",
 "setXFilesFactor",
 "sigmoid",
 "sin",
 "sinFunction",
 "smartSummarize",
 "sortBy",
 "sortByMaxima",
 "sortByMinima",
 "sortByName",
 "sortByTotal",
 "squareRoot",
 "stacked",
 "stddevSeries",
 "stdev",
 "substr",
 "sum",
 "sumSeries",
 "sumSeriesLists",
 "sumSeriesWithWildcards",
 "summarize",
 "threshold",
 "time",
 "timeFunction",
 "timeShift",
 "timeSlice",
 "timeStack",
 "transformNull",
 "unique",
 "useSeriesAbove",
 "verticalLine",
 "weightedAverage",
 "xFilesFactor"
]


def test_full_reference_function_parity():
    from victoriametrics_tpu.httpapi import graphite_api as ga
    missing = [n for n in GRAPHITE_FUNCTIONS_JSON if n not in ga._G_FUNCS]
    assert not missing, f"graphite functions missing: {missing}"
    assert len(GRAPHITE_FUNCTIONS_JSON) == 151
