"""Relabeling + stream aggregation tests (reference lib/promrelabel/
relabel_test.go + lib/streamaggr/streamaggr_test.go coverage style)."""

import math

import pytest

from victoriametrics_tpu.ingest.relabel import parse_relabel_configs
from victoriametrics_tpu.ingest.streamaggr import (Aggregator, Deduplicator,
                                                   StreamAggregators)

T0 = 1_753_700_000_000


def rl(yaml_text, labels):
    return parse_relabel_configs(yaml_text).apply(labels)


class TestRelabel:
    def test_replace(self):
        out = rl("""
- source_labels: [a, b]
  separator: "-"
  target_label: ab
  regex: "(.+)-(.+)"
  replacement: "$2_$1"
""", {"a": "x", "b": "y"})
        assert out["ab"] == "y_x"

    def test_replace_default_copies(self):
        out = rl("- {source_labels: [a], target_label: b}", {"a": "v"})
        assert out["b"] == "v"

    def test_keep_drop(self):
        cfg = '- {source_labels: [job], regex: "api|web", action: keep}'
        assert rl(cfg, {"job": "api"}) is not None
        assert rl(cfg, {"job": "db"}) is None
        cfg = '- {source_labels: [job], regex: "db", action: drop}'
        assert rl(cfg, {"job": "db"}) is None
        assert rl(cfg, {"job": "api"}) is not None

    def test_keep_drop_metrics(self):
        cfg = '- {regex: "http_.*", action: keep_metrics}'
        assert rl(cfg, {"__name__": "http_requests"}) is not None
        assert rl(cfg, {"__name__": "mem_bytes"}) is None

    def test_hashmod(self):
        out = rl("""
- {source_labels: [i], modulus: 4, target_label: shard, action: hashmod}
""", {"i": "host17"})
        assert out["shard"] in {"0", "1", "2", "3"}

    def test_labelmap(self):
        out = rl('- {regex: "__meta_(.+)", action: labelmap}',
                 {"__meta_dc": "eu", "keep": "1"})
        assert out["dc"] == "eu" and out["__meta_dc"] == "eu"

    def test_labeldrop_labelkeep(self):
        out = rl('- {regex: "tmp_.*", action: labeldrop}',
                 {"tmp_x": "1", "keep": "2"})
        assert out == {"keep": "2"}
        out = rl('- {regex: "keep", action: labelkeep}',
                 {"__name__": "m", "keep": "2", "other": "3"})
        assert out == {"__name__": "m", "keep": "2"}

    def test_case_actions(self):
        out = rl('- {source_labels: [a], target_label: a, action: uppercase}',
                 {"a": "low"})
        assert out["a"] == "LOW"

    def test_keep_if_equal(self):
        cfg = '- {source_labels: [a, b], action: keep_if_equal}'
        assert rl(cfg, {"a": "x", "b": "x"}) is not None
        assert rl(cfg, {"a": "x", "b": "y"}) is None

    def test_if_guard(self):
        cfg = """
- if: '{job="api"}'
  source_labels: [job]
  target_label: matched
  replacement: "yes"
"""
        assert rl(cfg, {"job": "api"})["matched"] == "yes"
        assert "matched" not in rl(cfg, {"job": "db"})

    def test_graphite(self):
        out = rl("""
- action: graphite
  match: "foo.*.baz"
  labels: {job: "$1_stats", __name__: "qux"}
""", {"__name__": "foo.bar.baz"})
        assert out["job"] == "bar_stats" and out["__name__"] == "qux"

    def test_chain_drops_empty_values(self):
        out = rl("""
- {source_labels: [a], target_label: b}
- {source_labels: [gone], target_label: a}
""", {"a": "v"})
        assert out == {"b": "v", "a": "v"} or out == {"b": "v"}


class TestStreamAggr:
    def collect(self):
        rows = []
        return rows, lambda batch: rows.extend(batch)

    def test_sum_and_count_by(self):
        rows, push = self.collect()
        a = Aggregator({"interval": "60s", "outputs": ["sum_samples",
                                                       "count_samples"],
                        "by": ["job"]}, push)
        for i in range(10):
            a.push({"__name__": "m", "job": "api", "pod": f"p{i}"},
                   T0 + i, float(i))
        a.flush(T0 + 60_000)
        byname = {r[0]["__name__"]: r for r in rows}
        assert byname["m:1m_sum_samples"][2] == 45.0
        assert byname["m:1m_count_samples"][2] == 10.0
        assert byname["m:1m_sum_samples"][0]["job"] == "api"
        assert "pod" not in byname["m:1m_sum_samples"][0]

    def test_total_handles_counter_resets(self):
        rows, push = self.collect()
        a = Aggregator({"interval": "1m", "outputs": ["total"]}, push)
        for ts, v in [(0, 10), (1, 20), (2, 5), (3, 8)]:  # reset at 5
            a.push({"__name__": "c"}, T0 + ts * 1000, float(v))
        a.flush(T0 + 60_000)
        # initial 10 + 10 + (reset->5) + 3
        assert rows[0][2] == 28.0

    def test_quantiles_and_unique(self):
        rows, push = self.collect()
        a = Aggregator({"interval": "1m",
                        "outputs": ["quantiles(0.5)", "unique_samples"]},
                       push)
        for v in [1, 2, 2, 3, 100]:
            a.push({"__name__": "m"}, T0, float(v))
        a.flush(T0 + 60_000)
        byname = {(r[0]["__name__"], r[0].get("quantile")): r[2]
                  for r in rows}
        assert byname[("m:1m_quantiles", "0.5")] == 2.0
        assert byname[("m:1m_unique_samples", None)] == 4.0

    def test_histogram_bucket(self):
        rows, push = self.collect()
        a = Aggregator({"interval": "1m", "outputs": ["histogram_bucket"]},
                       push)
        for v in [0.0005, 0.05, 0.5, 900]:
            a.push({"__name__": "lat"}, T0, v)
        a.flush(T0 + 60_000)
        cum = {r[0]["le"]: r[2] for r in rows}
        assert cum["0.001"] == 1.0 and cum["+Inf"] == 4.0

    def test_match_selector(self):
        rows, push = self.collect()
        sa = StreamAggregators([{"interval": "1m", "outputs": ["last"],
                                 "match": '{__name__=~"http_.*"}'}], push)
        assert sa.push({"__name__": "http_reqs"}, T0, 1.0)
        assert not sa.push({"__name__": "mem"}, T0, 1.0)
        sa.stop()
        assert rows[0][0]["__name__"] == "http_reqs:1m_last"

    def test_deduplicator(self):
        rows, push = self.collect()
        d = Deduplicator(30_000, push)
        d.push({"__name__": "m"}, T0, 1.0)
        d.push({"__name__": "m"}, T0 + 1000, 2.0)
        d.push({"__name__": "m2"}, T0, 5.0)
        d.flush()
        assert sorted((r[0]["__name__"], r[2]) for r in rows) == \
            [("m", 2.0), ("m2", 5.0)]

    def test_bad_config(self):
        with pytest.raises(ValueError):
            Aggregator({"interval": "1m", "outputs": ["bogus"]}, lambda b: 0)
        with pytest.raises(ValueError):
            Aggregator({"interval": "0s", "outputs": ["last"]}, lambda b: 0)
