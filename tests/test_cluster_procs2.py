"""Cluster apptest matrix beyond the basic 2-node scatter-gather
(reference apptest/tests/{replication,sharding,multilevel}_test.go as real
OS processes): RF=2 write fan-out with query-time replica dedup, node-loss
completeness under replication, rerouting around a PAUSED (SIGSTOP — still
accepting TCP, never answering) node, and a multilevel vmselect chain over
-clusternativeListenAddr."""

import json
import os
import signal
import time
import urllib.request

import pytest

from tests.apptest_helpers import AppProc, Client, free_ports

T0 = 1_753_700_000_000


def _metric(port: int, name: str) -> float:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=10) as r:
        text = r.read().decode()
    for ln in text.splitlines():
        if ln.startswith(name + " ") or ln.startswith(name + "{"):
            return float(ln.split()[-1])
    return 0.0


def _flush(port: int):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/internal/force_flush", timeout=10):
        pass


@pytest.fixture(scope="module")
def rf2(tmp_path_factory):
    """2x vmstorage + vminsert(RF=2) + vmselect."""
    d = tmp_path_factory.mktemp("rf2")
    (s1h, s1i, s1s, s2h, s2i, s2s, ih, sh) = free_ports(8)
    procs = []
    try:
        for n, (hh, ii, ss) in (("s1", (s1h, s1i, s1s)),
                                ("s2", (s2h, s2i, s2s))):
            procs.append(AppProc("vmstorage", [
                f"-storageDataPath={d}/{n}",
                f"-httpListenAddr=127.0.0.1:{hh}",
                f"-vminsertAddr=127.0.0.1:{ii}",
                f"-vmselectAddr=127.0.0.1:{ss}"], hh, f"vmstorage-{n}"))
        nodes = [f"-storageNode=127.0.0.1:{s1i}:{s1s}",
                 f"-storageNode=127.0.0.1:{s2i}:{s2s}"]
        procs.append(AppProc(
            "vminsert", nodes + [f"-httpListenAddr=127.0.0.1:{ih}",
                                 "-replicationFactor=2"], ih, "vminsert"))
        procs.append(AppProc(
            "vmselect", nodes + [f"-httpListenAddr=127.0.0.1:{sh}"],
            sh, "vmselect"))
        yield {"st": procs[:2], "vi": procs[2], "vs": procs[3],
               "sports": (s1h, s2h)}
    finally:
        for p in procs:
            p.stop(kill=True)


def test_rf2_full_replication_and_dedup(rf2):
    vi = Client(rf2["vi"].port)
    vs = Client(rf2["vs"].port)
    lines = [f'rfm{{series="{i}"}} {i} {T0 + k * 15000}'
             for i in range(100) for k in range(3)]
    code, _ = vi.post("/insert/0/prometheus/api/v1/import/prometheus",
                      "\n".join(lines).encode())
    assert code == 204
    for p in rf2["sports"]:
        _flush(p)
    # RF=2 over 2 nodes: EVERY row lands on BOTH nodes
    for p in rf2["sports"]:
        assert _metric(p, "vm_rows_added_to_storage_total") == 300.0
    # query-time replica dedup: each series exactly once, values intact
    code, body = vs.get("/select/0/prometheus/api/v1/query",
                        query="count(rfm)",
                        time=str((T0 + 30000) // 1000))
    res = json.loads(body)
    assert res["status"] == "success"
    assert float(res["data"]["result"][0]["value"][1]) == 100.0
    code, body = vs.get("/select/0/prometheus/api/v1/query",
                        query="sum(rfm)", time=str((T0 + 30000) // 1000))
    assert float(json.loads(body)["data"]["result"][0]["value"][1]) \
        == float(sum(range(100)))


def test_rf2_node_loss_keeps_full_results(rf2):
    """With RF=2 every series lives on the surviving node: results stay
    COMPLETE after a kill (apptest replication_test.go)."""
    vs = Client(rf2["vs"].port)
    rf2["st"][1].stop(kill=True)
    time.sleep(0.3)
    code, body = vs.get("/select/0/prometheus/api/v1/query",
                        query="count(rfm)",
                        time=str((T0 + 30000) // 1000))
    res = json.loads(body)
    assert res["status"] == "success"
    # completeness despite the lost node — replication, not luck
    assert float(res["data"]["result"][0]["value"][1]) == 100.0


@pytest.fixture()
def pausable(tmp_path_factory):
    """2x vmstorage + vminsert(RF=1, 1s RPC timeout) for reroute tests."""
    d = tmp_path_factory.mktemp("pause")
    (s1h, s1i, s1s, s2h, s2i, s2s, ih, sh) = free_ports(8)
    procs = []
    try:
        for n, (hh, ii, ss) in (("s1", (s1h, s1i, s1s)),
                                ("s2", (s2h, s2i, s2s))):
            procs.append(AppProc("vmstorage", [
                f"-storageDataPath={d}/{n}",
                f"-httpListenAddr=127.0.0.1:{hh}",
                f"-vminsertAddr=127.0.0.1:{ii}",
                f"-vmselectAddr=127.0.0.1:{ss}"], hh, f"vmstorage-{n}"))
        nodes = [f"-storageNode=127.0.0.1:{s1i}:{s1s}",
                 f"-storageNode=127.0.0.1:{s2i}:{s2s}"]
        procs.append(AppProc(
            "vminsert", nodes + [f"-httpListenAddr=127.0.0.1:{ih}",
                                 "-rpc.timeout=1.0"], ih, "vminsert"))
        procs.append(AppProc(
            "vmselect",
            [nodes[0], f"-httpListenAddr=127.0.0.1:{sh}",
             "-rpc.timeout=2.0"], sh, "vmselect"))
        yield {"st": procs[:2], "vi": procs[2], "vs": procs[3],
               "sports": (s1h, s2h)}
    finally:
        for p in procs:
            try:
                os.kill(p.proc.pid, signal.SIGCONT)
            except OSError:
                pass
            p.stop(kill=True)


def test_reroute_on_paused_node(pausable):
    """SIGSTOP (node alive at TCP level but unresponsive — the 'slow node'
    case, harder than a kill): writes must time out, mark the node down,
    and reroute its shard to the healthy node without losing rows."""
    vi = Client(pausable["vi"].port)
    vs = Client(pausable["vs"].port)
    # seed both shards while healthy so the hash ring places series on s2
    lines = [f'prm{{series="{i}"}} {i} {T0}' for i in range(40)]
    code, _ = vi.post("/insert/0/prometheus/api/v1/import/prometheus",
                      "\n".join(lines).encode())
    assert code == 204
    os.kill(pausable["st"][1].proc.pid, signal.SIGSTOP)
    t0 = time.time()
    lines = [f'prm{{series="{i}"}} {i + 1000} {T0 + 15000}'
             for i in range(40)]
    code, _ = vi.post("/insert/0/prometheus/api/v1/import/prometheus",
                      "\n".join(lines).encode())
    assert code == 204
    took = time.time() - t0
    assert took < 8, f"reroute too slow: {took:.1f}s"
    assert _metric(pausable["vi"].port, "vm_cluster_reroutes_total") > 0
    # every second-batch row survived on the healthy node: query through
    # the vmselect wired ONLY to s1
    _flush(pausable["sports"][0])
    code, body = vs.get("/select/0/prometheus/api/v1/query",
                        query='count(prm > 999)',
                        time=str((T0 + 15000) // 1000))
    res = json.loads(body)
    assert res["status"] == "success"
    assert float(res["data"]["result"][0]["value"][1]) == 40.0
    os.kill(pausable["st"][1].proc.pid, signal.SIGCONT)


@pytest.fixture(scope="module")
def multilevel(tmp_path_factory):
    """storage <- vminsert; storage <- vmselect-lower
    (-clusternativeListenAddr) <- vmselect-top: the top node treats the
    lower SELECT tier as its storage backend (multilevel federation)."""
    d = tmp_path_factory.mktemp("ml")
    (sh, si, ss, ih, lh, ln, th) = free_ports(7)
    procs = []
    try:
        procs.append(AppProc("vmstorage", [
            f"-storageDataPath={d}/s",
            f"-httpListenAddr=127.0.0.1:{sh}",
            f"-vminsertAddr=127.0.0.1:{si}",
            f"-vmselectAddr=127.0.0.1:{ss}"], sh, "vmstorage"))
        procs.append(AppProc("vminsert", [
            f"-storageNode=127.0.0.1:{si}:{ss}",
            f"-httpListenAddr=127.0.0.1:{ih}"], ih, "vminsert"))
        procs.append(AppProc("vmselect", [
            f"-storageNode=127.0.0.1:{si}:{ss}",
            f"-httpListenAddr=127.0.0.1:{lh}",
            f"-clusternativeListenAddr=127.0.0.1:{ln}"], lh,
            "vmselect-lower"))
        # top level: the lower vmselect's native port serves the SELECT
        # API; the insert port slot is a dummy (never dialed on reads)
        procs.append(AppProc("vmselect", [
            f"-storageNode=127.0.0.1:1:{ln}",
            f"-httpListenAddr=127.0.0.1:{th}"], th, "vmselect-top"))
        yield {"procs": procs, "sh": sh, "ih": ih, "lh": lh, "th": th}
    finally:
        for p in procs:
            p.stop(kill=True)


def test_multilevel_select_chain(multilevel):
    vi = Client(multilevel["ih"])
    lines = [f'mlm{{series="{i}"}} {i * 2} {T0}' for i in range(50)]
    code, _ = vi.post("/insert/0/prometheus/api/v1/import/prometheus",
                      "\n".join(lines).encode())
    assert code == 204
    _flush(multilevel["sh"])
    results = {}
    for tier in ("lh", "th"):
        c = Client(multilevel[tier])
        code, body = c.get("/select/0/prometheus/api/v1/query",
                           query="sum(mlm)", time=str(T0 // 1000))
        res = json.loads(body)
        assert res["status"] == "success", (tier, res)
        results[tier] = float(res["data"]["result"][0]["value"][1])
    assert results["lh"] == results["th"] == float(sum(i * 2
                                                       for i in range(50)))
    # series-level reads traverse the chain too
    c = Client(multilevel["th"])
    code, body = c.get("/select/0/prometheus/api/v1/series",
                       **{"match[]": "mlm", "start": str(T0 // 1000 - 60),
                          "end": str(T0 // 1000 + 60)})
    assert code == 200
    assert len(json.loads(body)["data"]) == 50
