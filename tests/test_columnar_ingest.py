"""Columnar native-ingest path: differential tests against the Python
parsers/row path (the reference treats all protocol parsers as hot paths,
lib/protoparser/*; here each parser must agree with its Python twin and
Storage.add_rows_columnar must agree with Storage.add_rows)."""

import random
import time

import numpy as np
import pytest

from victoriametrics_tpu import native
from victoriametrics_tpu.ingest import parsers, remote_write, snappy
from victoriametrics_tpu.storage.storage import Storage
from victoriametrics_tpu.storage.tag_filters import filters_from_dict

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")

T0 = 1_753_700_000_000


# -- parsers ----------------------------------------------------------------

class TestSnappy:
    def test_roundtrip(self):
        rng = random.Random(7)
        for payload in (b"", b"x", b"hello world" * 400,
                        bytes(rng.randrange(256) for _ in range(10_000)),
                        b"ab" * 50_000):
            assert native.snappy_uncompress(snappy.compress(payload)) \
                == payload

    def test_malformed(self):
        assert native.snappy_uncompress(b"\xff\xff\xff\xff\xff") is None


def rw_roundtrip(series, default_ts=T0):
    raw = remote_write.build_write_request(series, compress="")
    cr = native.parse_rw_columnar(raw, default_ts)
    assert cr is not None
    return cr.to_rows()


class TestRemoteWriteParse:
    def test_matches_python(self):
        series = []
        for i in range(50):
            labels = [("__name__", "m"), ("idx", str(i)), ("job", "api")]
            samples = [(T0 + j, float(i + j)) for j in range(4)]
            series.append((labels, samples))
        rows = rw_roundtrip(series)
        raw = remote_write.build_write_request(series, compress="")
        py = [(dict(labels), ts, val)
              for labels, samples in remote_write.parse_write_request(raw, "none")
              for ts, val in samples]
        assert len(rows) == len(py) == 200
        for (key, ts, val), (plabels, pts, pval) in zip(rows, py):
            assert dict(parsers.labels_from_series_key(key)) == plabels
            assert ts == pts and val == pval

    def test_zero_ts_defaults(self):
        rows = rw_roundtrip([([("__name__", "m")], [(0, 1.0)])], 4242)
        assert rows == [(b"m", 4242, 1.0)]

    def test_value_escaping_roundtrips(self):
        labels = [("__name__", "m"), ("p", 'a\\b"c\nd,e=f')]
        rows = rw_roundtrip([(labels, [(T0, 1.0)])])
        assert parsers.labels_from_series_key(rows[0][0]) == labels

    def test_weird_label_name_falls_back(self):
        raw = remote_write.build_write_request(
            [([("__name__", "m"), ("bad label", "v")], [(T0, 1.0)])],
            compress="")
        assert native.parse_rw_columnar(raw, T0) is None

    def test_missing_name_falls_back(self):
        raw = remote_write.build_write_request(
            [([("job", "api")], [(T0, 1.0)])], compress="")
        assert native.parse_rw_columnar(raw, T0) is None

    def test_nan_inf_values(self):
        rows = rw_roundtrip([([("__name__", "m")],
                              [(T0, float("inf")), (T0 + 1, float("nan"))])])
        assert rows[0][2] == float("inf") and np.isnan(rows[1][2])


class TestInfluxParse:
    CASES = [
        b"cpu,host=h1 usage=1.5 1753700000000000000",
        b"cpu value=7",
        b"cpu,host=h1,region=r usage=1,idle=99i,frac=2.5,flag=t,off=F",
        b"m field=1u\nm2 value=-3.25e2 1753700000123000000",
        b"m,t=a\\,b\\ c,u=q\\=r v=1",
        b"drop msg=\"a string\",ok=2",
        b"tagless value=0.5\n# comment\n\nweird,empty=,k=v f=1",
        b"neg v=1 -1753700000000000001",
    ]

    @pytest.mark.parametrize("body", CASES)
    def test_matches_python(self, body):
        cr = native.parse_influx_columnar(body, "db0", T0)
        assert cr is not None
        rows = cr.to_rows()
        py = list(parsers.parse_influx(body.decode(), T0, db="db0"))
        assert len(rows) == len(py)
        for (key, ts, val), prow in zip(rows, py):
            assert dict(parsers.labels_from_series_key(key)) \
                == dict(prow.labels)
            assert ts == prow.timestamp
            assert val == prow.value

    def test_no_db(self):
        rows = native.parse_influx_columnar(b"cpu usage=1", "", T0).to_rows()
        assert rows == [(b"cpu_usage", T0, 1.0)]

    def test_metachar_measurement_falls_back(self):
        # a measurement with ',' cannot round-trip through a text series
        # key: the native parser must defer to the Python path
        assert native.parse_influx_columnar(
            b"esc\\,aped v=1", "", T0) is None

    def test_float_ts_falls_back(self):
        # Python int() raises on float timestamps; native must defer, not
        # silently diverge
        assert native.parse_influx_columnar(b"cpu v=1 1.5e18", "", T0) is None


class TestKeyMap:
    def test_ids_first_occurrence_order(self):
        km = native.KeyMap()
        base = b"aaabbbcccaaa"
        off = np.array([0, 3, 6, 9], np.int64)
        ln = np.array([3, 3, 3, 3], np.int64)
        ids, new = km.resolve(base, off, ln)
        assert list(ids) == [0, 1, 2, 0] and new == 3
        ids2, new2 = km.resolve(base, off, ln)
        assert list(ids2) == [0, 1, 2, 0] and new2 == 0 and len(km) == 3
        km.close()

    def test_growth(self):
        km = native.KeyMap()
        keys = b"".join(b"key%07d" % i for i in range(50_000))
        off = np.arange(50_000, dtype=np.int64) * 10
        ln = np.full(50_000, 10, np.int64)
        ids, new = km.resolve(keys, off, ln)
        assert new == 50_000 and list(ids[:3]) == [0, 1, 2]
        ids2, new2 = km.resolve(keys, off, ln)
        assert new2 == 0 and (ids2 == ids).all()
        km.close()


# -- storage columnar path --------------------------------------------------

def fetch_all(st, name, lo=T0 - 10 ** 9, hi=T0 + 10 ** 9, tenant=(0, 0)):
    out = {}
    for sd in st.search_series(filters_from_dict({"__name__": name}), lo, hi,
                               tenant=tenant):
        key = tuple(sorted([(b"__name__", sd.metric_name.metric_group)]
                           + list(sd.metric_name.labels)))
        out[key] = (list(sd.timestamps), [round(v, 10) for v in sd.values])
    return out


def prom_body(n=200, it=0):
    return ("\n".join(
        f'cm{{idx="{i}",job="j{i % 5}"}} {i + it}.5 {T0 + it * 1000}'
        for i in range(n))).encode()


class TestAddRowsColumnar:
    def test_matches_add_rows(self, tmp_path):
        st_a = Storage(str(tmp_path / "a"))
        st_b = Storage(str(tmp_path / "b"))
        try:
            for it in range(3):
                body = prom_body(it=it)
                cr = native.parse_prom_columnar(body, T0)
                assert cr is not None
                n_a = st_a.add_rows_columnar(cr)
                rows = [(dict(parsers.labels_from_series_key(k)), ts, v)
                        for k, ts, v in cr.to_rows()]
                n_b = st_b.add_rows(rows)
                assert n_a == n_b == 200
            res_a = fetch_all(st_a, "cm")
            assert len(res_a) == 200
            assert res_a == fetch_all(st_b, "cm")
        finally:
            st_a.close()
            st_b.close()

    def test_mixed_tuple_and_columnar(self, tmp_path):
        # both paths interleaved into ONE storage: flush must merge
        # PendingChunks and tuple rows into correctly sorted parts
        st = Storage(str(tmp_path / "s"))
        try:
            cr = native.parse_prom_columnar(prom_body(50, 0), T0)
            st.add_rows_columnar(cr)
            rows = [({"__name__": "cm", "idx": str(i), "job": f"j{i % 5}"},
                     T0 + 1000, float(i)) for i in range(50)]
            st.add_rows(rows)
            st.add_rows_columnar(native.parse_prom_columnar(
                prom_body(50, 2), T0))
            st.table.flush_to_disk()
            res = fetch_all(st, "cm")
            assert len(res) == 50
            key = tuple(sorted([(b"__name__", b"cm"), (b"idx", b"7"),
                                (b"job", b"j2")]))
            ts, vals = res[key]
            assert ts == [T0, T0 + 1000, T0 + 2000]
            assert vals == [7.5, 7.0, 9.5]
        finally:
            st.close()

    def test_transform_relabel_caches_per_series(self, tmp_path):
        st = Storage(str(tmp_path / "s"))
        calls = []

        def transform(labels):
            calls.append(1)
            d = dict(labels)
            if d.get("idx") == "1":
                return None  # dropped
            d["extra"] = "yes"
            return list(d.items())

        try:
            body = prom_body(4)
            stats = {}
            n = st.add_rows_columnar(native.parse_prom_columnar(body, T0),
                                     transform=transform, drop_stats=stats)
            assert n == 3 and stats == {"transform": 1}
            n_calls = len(calls)
            assert n_calls == 4  # once per new series
            # repeat batch: verdicts cached, transform never re-runs
            stats2 = {}
            n2 = st.add_rows_columnar(
                native.parse_prom_columnar(prom_body(4, 1), T0),
                transform=transform, drop_stats=stats2)
            assert n2 == 3 and len(calls) == n_calls
            assert stats2 == {"transform": 1}
            res = fetch_all(st, "cm")
            assert len(res) == 3
            assert all(dict(k)[b"extra"] == b"yes" for k in res)
            # reset invalidates the cached verdicts
            st.reset_columnar_spaces()
            st.add_rows_columnar(
                native.parse_prom_columnar(prom_body(4, 2), T0),
                transform=transform)
            assert len(calls) == n_calls + 4
        finally:
            st.close()

    def test_malformed_key_skips_row_keeps_batch(self, tmp_path):
        st = Storage(str(tmp_path / "s"))
        try:
            body = b'good{a="1"} 1 ' + str(T0).encode() + \
                b'\nbad{a="unterminated 2\ngood2 3 ' + str(T0).encode()
            cr = native.parse_prom_columnar(body, T0)
            # native text parser already drops the unterminated line
            n = st.add_rows_columnar(cr)
            assert n == 2
        finally:
            st.close()

    def test_day_rollover_creates_per_day_indexes(self, tmp_path):
        st = Storage(str(tmp_path / "s"))
        try:
            day0 = (T0 // 86_400_000) * 86_400_000
            body = (f'dm{{i="0"}} 1 {day0}\n'
                    f'dm{{i="0"}} 2 {day0 + 86_400_000}\n').encode()
            st.add_rows_columnar(native.parse_prom_columnar(body, T0))
            st.table.flush_pending()
            # per-day postings: search restricted to each day finds it
            for d in (day0, day0 + 86_400_000):
                res = st.search_series(filters_from_dict({"__name__": "dm"}),
                                       d, d + 3_600_000)
                assert len(res) == 1
        finally:
            st.close()

    def test_month_straddle_routes_partitions(self, tmp_path):
        st = Storage(str(tmp_path / "s"))
        try:
            jul = 1_753_900_000_000   # 2025-07-30
            aug = 1_754_100_000_000   # 2025-08-02
            body = (f'mm{{i="0"}} 1 {jul}\nmm{{i="0"}} 2 {aug}\n').encode()
            st.add_rows_columnar(native.parse_prom_columnar(body, jul))
            st.table.flush_to_disk()
            assert len(st.table.partitions_for_range(jul, aug)) == 2
            res = fetch_all(st, "mm", jul - 1, aug + 1)
            assert list(res.values())[0][0] == [jul, aug]
        finally:
            st.close()

    def test_cardinality_limiter_applies(self, tmp_path):
        st = Storage(str(tmp_path / "s"), max_hourly_series=3)
        try:
            stats = {}
            st.add_rows_columnar(
                native.parse_prom_columnar(prom_body(10), T0),
                drop_stats=stats)
            st.table.flush_pending()
            res = fetch_all(st, "cm")
            assert len(res) <= 3
        finally:
            st.close()

    def test_cardinality_rejection_is_retried(self, tmp_path):
        # a series rejected under limiter pressure must be re-judged per
        # batch (limiter windows rotate) — the drop verdict is not sticky
        st = Storage(str(tmp_path / "s"), max_hourly_series=3)
        try:
            st.add_rows_columnar(native.parse_prom_columnar(
                prom_body(10), T0))
            st.table.flush_pending()
            admitted0 = len(fetch_all(st, "cm"))
            assert admitted0 <= 3
            # rotate the hourly window, then resend: previously rejected
            # series must be admitted now
            st.hourly_limiter._bucket = -1  # force window rotation
            st.add_rows_columnar(native.parse_prom_columnar(
                prom_body(10, 1), T0))
            st.table.flush_pending()
            assert len(fetch_all(st, "cm")) > admitted0
        finally:
            st.close()

    def test_space_reset_bounds_memory(self, tmp_path):
        from victoriametrics_tpu.storage.storage import _ColumnarSpace
        st = Storage(str(tmp_path / "s"))
        old_max = _ColumnarSpace.MAX_KEYS
        _ColumnarSpace.MAX_KEYS = 8
        try:
            for it in range(4):
                st.add_rows_columnar(native.parse_prom_columnar(
                    prom_body(6, it), T0))
            sp = st._cspaces[(0, 0)]
            assert len(sp.keymap) <= 8 + 6  # reset happened at least once
            st.table.flush_pending()
            assert len(fetch_all(st, "cm")) == 6  # data survived the resets
        finally:
            _ColumnarSpace.MAX_KEYS = old_max
            st.close()

    def test_tenant_isolation(self, tmp_path):
        st = Storage(str(tmp_path / "s"))
        try:
            st.add_rows_columnar(native.parse_prom_columnar(
                b"tm 1 " + str(T0).encode(), T0), tenant=(1, 2))
            st.add_rows_columnar(native.parse_prom_columnar(
                b"tm 9 " + str(T0).encode(), T0), tenant=(3, 4))
            st.table.flush_pending()
            a = fetch_all(st, "tm", tenant=(1, 2))
            b = fetch_all(st, "tm", tenant=(3, 4))
            assert list(a.values()) == [([T0], [1.0])]
            assert list(b.values()) == [([T0], [9.0])]
        finally:
            st.close()


# -- HTTP layer -------------------------------------------------------------

@pytest.fixture()
def api(tmp_path):
    from victoriametrics_tpu.httpapi.prometheus_api import PrometheusAPI
    st = Storage(str(tmp_path / "data"))
    a = PrometheusAPI(st)
    yield a
    st.close()


class FakeReq:
    def __init__(self, body, args=None):
        self.body = body
        self.args = args or {}

    def arg(self, name, default=""):
        return self.args.get(name, default)


class TestHTTPColumnar:
    def test_remote_write_snappy_fast_path(self, api):
        series = [([("__name__", "hm"), ("i", str(i))], [(T0, float(i))])
                  for i in range(8)]
        body = remote_write.build_write_request(series, compress="snappy")
        resp = api.h_remote_write(FakeReq(body))
        assert resp.status == 204
        assert api.rows_inserted == 8
        api.storage.table.flush_pending()
        assert len(fetch_all(api.storage, "hm")) == 8

    def test_influx_fast_path_matches_slow(self, api):
        body = (f"cpu,host=a usage=1.25,idle=2 {T0 * 1_000_000}\n"
                f"cpu,host=b usage=7 {T0 * 1_000_000}").encode()
        resp = api.h_influx_write(FakeReq(body, {"db": "telegraf"}))
        assert resp.status == 204 and api.rows_inserted == 3
        api.storage.table.flush_pending()
        res = fetch_all(api.storage, "cpu_usage")
        assert len(res) == 2
        assert dict(list(res)[0])[b"db"] == b"telegraf"

    def test_fast_path_composes_with_relabel(self, api, tmp_path):
        from victoriametrics_tpu.ingest.relabel import parse_relabel_configs
        api.relabel = parse_relabel_configs(
            "- action: drop\n"
            "  source_labels: [idx]\n"
            "  regex: '1'\n"
            "- action: replace\n"
            "  target_label: dc\n"
            "  replacement: eu\n")
        req = FakeReq(prom_body(4))
        assert api.h_import_prometheus(req).status == 204
        assert api.rows_inserted == 3
        assert api.rows_relabel_dropped == 1
        # repeat: cached verdicts, counters still advance per row
        assert api.h_import_prometheus(FakeReq(prom_body(4, 1))).status == 204
        assert api.rows_inserted == 6
        assert api.rows_relabel_dropped == 2
        api.storage.table.flush_pending()
        res = fetch_all(api.storage, "cm")
        assert len(res) == 3
        assert all(dict(k)[b"dc"] == b"eu" for k in res)

    def test_relabel_reload_resets_cache(self, api):
        from victoriametrics_tpu.ingest.relabel import parse_relabel_configs
        assert api.h_import_prometheus(FakeReq(prom_body(4))).status == 204
        assert api.rows_inserted == 4
        api.relabel = parse_relabel_configs(
            "- action: drop\n  source_labels: [idx]\n  regex: '.*'\n")
        assert api.h_import_prometheus(
            FakeReq(prom_body(4, 1))).status == 204
        assert api.rows_inserted == 4  # everything dropped post-reload

    def test_series_limits_compose(self, api):
        from victoriametrics_tpu.ingest.serieslimits import SeriesLimits
        api.series_limits = SeriesLimits(max_labels_per_series=1)
        assert api.h_import_prometheus(FakeReq(prom_body(3))).status == 204
        assert api.rows_inserted == 0  # cm has 2 labels + name
        assert api.h_import_prometheus(
            FakeReq(b"solo 1 " + str(T0).encode())).status == 204
        assert api.rows_inserted == 1
