"""Lockset-inference pass tests (devtools/lockset.py, rule VMT015).

Fixture packages are synthesized in tmp_path so the pass runs against a
known call graph: a field written from two concurrency roots with no
common lock must be flagged with both witness chains; the consistently
guarded twin — including guards inherited interprocedurally from a
locked caller — must be clean.  Also pins the runtime fix VMT015
forced: SLOEngine.expr_evals no longer loses updates under the
deterministic scheduler (the counters moved under the engine lock)."""

import textwrap

from victoriametrics_tpu.devtools import lockset as ls

# An RPC dispatch dict is recognized as a serving entry when it has
# >= 3 "*_vN" string keys mapping to same-module handler names.
_DISPATCH = """
        HANDLERS = {
            "a_v1": h_a,
            "b_v1": h_b,
            "c_v1": h_c,
        }
"""


def _write_pkg(tmp_path, body: str):
    d = tmp_path / "fixture_pkg"
    d.mkdir()
    (d / "srv.py").write_text(textwrap.dedent(body), encoding="utf-8")
    return d


def test_unguarded_two_root_write_is_flagged(tmp_path):
    """Two serving entries funneling into the same unguarded module-
    global write: the race condition proper."""
    pkg = _write_pkg(tmp_path, """
        import threading

        STATS = {}
        MU = threading.Lock()

        def locked_read():
            with MU:
                return len(STATS)

        def bump():
            STATS["k"] = 1

        def h_a(r):
            bump()

        def h_b(r):
            bump()

        def h_c(r):
            pass
    """ + _DISPATCH)
    findings, _used = ls.run_pass(paths=[str(pkg)])
    assert len(findings) == 1, [f.message for f in findings]
    f = findings[0]
    assert f.rule == ls.RULE_ID
    assert "STATS" in f.message and "no consistent guard" in f.message
    # both witness chains name their entry handler
    assert "h_a" in f.message and "h_b" in f.message


def test_guarded_everywhere_is_clean(tmp_path):
    pkg = _write_pkg(tmp_path, """
        import threading

        STATS = {}
        MU = threading.Lock()

        def bump():
            with MU:
                STATS["k"] = 1

        def h_a(r):
            bump()

        def h_b(r):
            bump()

        def h_c(r):
            pass
    """ + _DISPATCH)
    findings, _used = ls.run_pass(paths=[str(pkg)])
    assert findings == [], [f.message for f in findings]


def test_mixed_guard_is_flagged(tmp_path):
    """One root takes the lock, the other does not — the disjoint pair
    is exactly the bug class (a 'mostly guarded' field is unguarded)."""
    pkg = _write_pkg(tmp_path, """
        import threading

        STATS = {}
        MU = threading.Lock()

        def bump_locked():
            with MU:
                STATS["k"] = 1

        def bump_bare():
            STATS["k"] = 2

        def h_a(r):
            bump_locked()

        def h_b(r):
            bump_bare()

        def h_c(r):
            pass
    """ + _DISPATCH)
    findings, _used = ls.run_pass(paths=[str(pkg)])
    assert len(findings) == 1, [f.message for f in findings]
    assert "bump_bare" in findings[0].message


def test_cross_call_guard_propagates(tmp_path):
    """The write site itself has no ``with`` — the lock is held by the
    CALLER on every path, which the per-root lockset intersection must
    recognize as a consistent guard."""
    pkg = _write_pkg(tmp_path, """
        import threading

        STATS = {}
        MU = threading.Lock()

        def inner():
            STATS["k"] = 1

        def outer():
            with MU:
                inner()

        def h_a(r):
            outer()

        def h_b(r):
            outer()

        def h_c(r):
            pass
    """ + _DISPATCH)
    findings, _used = ls.run_pass(paths=[str(pkg)])
    assert findings == [], [f.message for f in findings]


def test_cross_call_one_unlocked_path_is_flagged(tmp_path):
    """Same write site, but one root reaches it around the locked
    caller: the path intersection drops the lock and the pair races."""
    pkg = _write_pkg(tmp_path, """
        import threading

        STATS = {}
        MU = threading.Lock()

        def inner():
            STATS["k"] = 1

        def outer():
            with MU:
                inner()

        def h_a(r):
            outer()

        def h_b(r):
            inner()

        def h_c(r):
            pass
    """ + _DISPATCH)
    findings, _used = ls.run_pass(paths=[str(pkg)])
    assert len(findings) == 1, [f.message for f in findings]


def test_thread_target_is_a_root(tmp_path):
    """A ``threading.Thread(target=...)`` spawn makes the target its own
    concurrency root — one serving entry plus one background thread is
    already a two-root race."""
    pkg = _write_pkg(tmp_path, """
        import threading

        STATS = {}
        MU = threading.Lock()

        def locked_read():
            with MU:
                return len(STATS)

        def worker():
            STATS["k"] = 2

        def start():
            threading.Thread(target=worker).start()

        def h_a(r):
            STATS["k"] = 1

        def h_b(r):
            pass

        def h_c(r):
            pass
    """ + _DISPATCH)
    findings, _used = ls.run_pass(paths=[str(pkg)])
    assert len(findings) == 1, [f.message for f in findings]
    assert "thread worker" in findings[0].message


def test_single_root_is_not_flagged(tmp_path):
    """One root cannot race with itself — handler-serial mutation is
    out of scope no matter how unguarded it looks."""
    pkg = _write_pkg(tmp_path, """
        import threading

        STATS = {}
        MU = threading.Lock()

        def locked_read():
            with MU:
                return len(STATS)

        def h_a(r):
            STATS["k"] = 1

        def h_b(r):
            pass

        def h_c(r):
            pass
    """ + _DISPATCH)
    findings, _used = ls.run_pass(paths=[str(pkg)])
    assert findings == [], [f.message for f in findings]


def test_suppressed_access_site_counts_as_used(tmp_path):
    """A disable on ANY access site of the field suppresses the finding
    and is reported consumed (so VMT013 won't call it stale)."""
    pkg = _write_pkg(tmp_path, """
        import threading

        STATS = {}
        MU = threading.Lock()

        def locked_read():
            with MU:
                return len(STATS)

        def bump():
            STATS["k"] = 1  # vmt: disable=VMT015

        def h_a(r):
            bump()

        def h_b(r):
            bump()

        def h_c(r):
            pass
    """ + _DISPATCH)
    findings, used = ls.run_pass(paths=[str(pkg)])
    assert findings == [], [f.message for f in findings]
    (rel,) = used
    assert any(rule == ls.RULE_ID for _ln, rule in used[rel])


def test_repo_tree_is_clean():
    """The real tree carries ZERO baselined VMT015 findings — the races
    the pass found were fixed (or disabled with their invariant), not
    grandfathered."""
    findings, _used = ls.run_pass()
    assert findings == [], [f.message for f in findings]


# -- the runtime fix VMT015 forced ------------------------------------------

def test_sloplane_counters_keep_no_lost_updates():
    """VMT015 flagged SLOEngine.expr_evals: written from the self-scrape
    tick and the ``?pump=1`` HTTP seam with no common lock.  Pre-fix,
    the deterministic scheduler reproduced lost updates (9/12 at
    seed=1); post-fix (counters under the engine lock) every
    interleaving lands 12/12 with zero sanitizer reports."""
    from victoriametrics_tpu.devtools import racetrace, sched
    from victoriametrics_tpu.query.sloplane import SLOEngine, SLOSpec

    class _Streams:
        def instant_vector(self, expr, ts_ms, tenant):
            return []

    class _API:
        matstreams = _Streams()

    names = ("expr_evals",)
    racetrace.traced_fields(*names)(SLOEngine)
    try:
        for seed in range(5):
            racetrace.reset()
            racetrace.enable()
            try:
                eng = SLOEngine(
                    api=_API(),
                    specs=[SLOSpec("t", 99.0,
                                   {"bad": "bad{w}", "total": "tot{w}"})],
                    windows=[("5m", "1h", 14.4)],
                    interval_s=0.05, period="24h")
                s = sched.DeterministicScheduler(seed=seed)
                s.spawn("t0", lambda: eng.maybe_eval(force=True))
                s.spawn("t1", lambda: eng.maybe_eval(force=True))
                s.run(timeout=30)
                # 2 rounds x 2 exprs x 3 windows
                assert eng.expr_evals == 12, \
                    f"seed={seed}: lost update ({eng.expr_evals}/12)"
                races = [r for r in racetrace.reports()
                         if r.field == "expr_evals"]
                assert races == [], races
            finally:
                racetrace.disable()
    finally:
        try:
            racetrace._registry.remove((SLOEngine, names))
        except ValueError:
            pass
