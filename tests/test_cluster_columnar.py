"""Cluster columnar data plane: writeRowsColumnar_v1 sharding /
replication / rerouting / relabeling, searchColumns_v1 scatter-gather,
and equivalence with both the per-row RPC path and a single-node
Storage."""

import numpy as np
import pytest

from victoriametrics_tpu import native
from victoriametrics_tpu.parallel.cluster_api import (ClusterStorage,
                                                      StorageNodeClient,
                                                      make_storage_handlers)
from victoriametrics_tpu.parallel.rpc import (HELLO_INSERT, HELLO_SELECT,
                                              RPCServer)
from victoriametrics_tpu.storage.storage import Storage
from victoriametrics_tpu.storage.tag_filters import filters_from_dict

T0 = 1_753_700_000_000
pytestmark = pytest.mark.skipif(not native.available(),
                                reason="needs native lib")


class StorageNode:
    def __init__(self, path, legacy=False):
        self.storage = Storage(str(path))
        handlers = make_storage_handlers(self.storage)
        if legacy:  # a node from before the columnar protocol
            handlers.pop("writeRowsColumnar_v1")
            handlers.pop("searchColumns_v1")
        self.insert_srv = RPCServer("127.0.0.1", 0, HELLO_INSERT, handlers)
        self.select_srv = RPCServer("127.0.0.1", 0, HELLO_SELECT, handlers)
        self.insert_srv.start()
        self.select_srv.start()

    def client(self):
        return StorageNodeClient("127.0.0.1", self.insert_srv.port,
                                 self.select_srv.port)

    def stop(self):
        self.insert_srv.stop()
        self.select_srv.stop()
        self.storage.close()


def make_nodes(tmp_path, n=3, legacy_idx=()):
    return [StorageNode(tmp_path / f"n{i}", legacy=i in legacy_idx)
            for i in range(n)]


def columnar_batch(n_series=40, n_samples=12):
    keys = [f'ccm{{idx="{i}",job="j{i % 4}"}}'.encode()
            for i in range(n_series)]
    keybuf = b"".join(keys)
    klens = np.fromiter((len(k) for k in keys), np.int64, n_series)
    koffs = np.concatenate([[0], np.cumsum(klens)[:-1]])
    ts = (T0 + np.arange(n_samples, dtype=np.int64)[None, :] * 15_000)
    ts = np.broadcast_to(ts, (n_series, n_samples)).reshape(-1)
    vals = (np.arange(n_series, dtype=np.float64)[:, None] * 100 +
            np.arange(n_samples)[None, :]).reshape(-1)
    return native.ColumnarRows(keybuf, np.repeat(koffs, n_samples),
                               np.repeat(klens, n_samples),
                               ts.copy(), vals.copy())


def fetch_all(cluster, name="ccm"):
    cols = cluster.search_columns(filters_from_dict({"__name__": name}),
                                  T0 - 10**6, T0 + 10**9)
    out = {}
    for s in range(cols.n_series):
        n = int(cols.counts[s])
        out[cols.raw_names[s]] = (cols.ts[s, :n].tolist(),
                                  cols.vals[s, :n].tolist())
    return out


class TestColumnarWrite:
    def test_shards_and_reads_back(self, tmp_path):
        nodes = make_nodes(tmp_path)
        try:
            cluster = ClusterStorage([n.client() for n in nodes])
            n_ok = cluster.add_rows_columnar(columnar_batch())
            assert n_ok == 40 * 12
            for n in nodes:
                n.storage.force_flush()
            per_node = [n.storage.series_count() for n in nodes]
            assert sum(per_node) == 40
            assert all(c > 0 for c in per_node)
            res = fetch_all(cluster)
            assert len(res) == 40
            for raw, (ts, vals) in res.items():
                assert len(ts) == 12
            cluster.close()
        finally:
            for n in nodes:
                n.stop()

    def test_replication_and_replica_dedup(self, tmp_path):
        nodes = make_nodes(tmp_path)
        try:
            cluster = ClusterStorage([n.client() for n in nodes],
                                     replication_factor=2)
            cluster.add_rows_columnar(columnar_batch())
            per_node = [n.storage.series_count() for n in nodes]
            assert sum(per_node) == 80  # each series on exactly 2 nodes
            res = fetch_all(cluster)
            assert len(res) == 40
            assert all(len(ts) == 12 for ts, _ in res.values())
            cluster.close()
        finally:
            for n in nodes:
                n.stop()

    def test_reroute_on_dead_node(self, tmp_path):
        nodes = make_nodes(tmp_path)
        try:
            cluster = ClusterStorage([n.client() for n in nodes])
            nodes[1].stop()
            n_ok = cluster.add_rows_columnar(columnar_batch())
            assert n_ok == 40 * 12
            live = [nodes[0], nodes[2]]
            assert sum(n.storage.series_count() for n in live) == 40
            cluster.close()
        finally:
            for n in nodes:
                try:
                    n.stop()
                except Exception:
                    pass

    def test_transform_relabels_before_sharding(self, tmp_path):
        """The vminsert-side relabel verdict applies per distinct key and
        the TRANSFORMED key ships to storage."""
        nodes = make_nodes(tmp_path, n=2)
        try:
            cluster = ClusterStorage([n.client() for n in nodes])

            def transform(labels):
                d = dict(labels)
                if d.get("idx") == "0":
                    return None  # drop series 0
                d["dc"] = "eu"
                return list(d.items())

            stats = {}
            n_ok = cluster.add_rows_columnar(columnar_batch(),
                                             transform=transform,
                                             drop_stats=stats)
            assert n_ok == 39 * 12
            assert stats["transform"] == 12
            res = fetch_all(cluster)
            assert len(res) == 39
            for raw in res:
                assert b'dc\x01eu' in raw or b'dc' in raw
            cluster.close()
        finally:
            for n in nodes:
                n.stop()

    def test_unroundtrippable_transformed_name_uses_legacy_path(
            self, tmp_path):
        """A transform emitting label names with key-syntax bytes can't
        ride the text-key protocol; those series take the per-row
        canonical path and still land."""
        nodes = make_nodes(tmp_path, n=2)
        try:
            cluster = ClusterStorage([n.client() for n in nodes])

            def transform(labels):
                d = dict(labels)
                d['weird="x"'] = "v"  # label name with quote/equals
                return list(d.items())

            n_ok = cluster.add_rows_columnar(columnar_batch(n_series=5),
                                             transform=transform)
            assert n_ok == 5 * 12
            assert sum(n.storage.series_count() for n in nodes) == 5
            # and the weird label survived end-to-end
            res = cluster.search_series(
                filters_from_dict({"__name__": "ccm"}), T0 - 10**6,
                T0 + 10**9)
            assert len(res) == 5
            for sd in res:
                assert sd.metric_name.get_label(b'weird="x"') == b"v"
            cluster.close()
        finally:
            for n in nodes:
                n.stop()

    def test_rpc_and_transform_paths_do_not_share_verdicts(self, tmp_path):
        """transform=None ingest (multilevel RPC) must not poison the
        relabel path's verdict cache and vice versa."""
        nodes = make_nodes(tmp_path, n=2)
        try:
            cluster = ClusterStorage([n.client() for n in nodes])
            batch = columnar_batch(n_series=4)
            cluster.add_rows_columnar(batch)  # no transform (RPC path)

            def transform(labels):
                d = dict(labels)
                if d.get("idx") == "1":
                    return None  # drop
                d["dc"] = "eu"
                return list(d.items())

            stats: dict = {}
            n_ok = cluster.add_rows_columnar(columnar_batch(n_series=4),
                                             transform=transform,
                                             drop_stats=stats)
            # the drop rule MUST fire even though the keys were already
            # seen by the no-transform path
            assert n_ok == 3 * 12
            assert stats.get("transform") == 12
            cluster.close()
        finally:
            for n in nodes:
                n.stop()

    def test_legacy_node_fallback(self, tmp_path):
        """A node without the columnar RPCs still ingests (per-row
        fallback) and serves reads (search_v1 adapter)."""
        nodes = make_nodes(tmp_path, n=2, legacy_idx=(1,))
        try:
            cluster = ClusterStorage([n.client() for n in nodes])
            n_ok = cluster.add_rows_columnar(columnar_batch())
            assert n_ok == 40 * 12
            assert sum(n.storage.series_count() for n in nodes) == 40
            assert nodes[1].storage.series_count() > 0  # legacy got rows
            res = fetch_all(cluster)
            assert len(res) == 40
            assert all(len(ts) == 12 for ts, _ in res.values())
            cluster.close()
        finally:
            for n in nodes:
                n.stop()


class TestColumnarReadEquivalence:
    def test_matches_single_node_storage(self, tmp_path):
        """Cluster columnar read == single-node Storage.search_columns on
        identical data (values, timestamps, names, order)."""
        nodes = make_nodes(tmp_path, n=3)
        single = Storage(str(tmp_path / "single"))
        try:
            cluster = ClusterStorage([n.client() for n in nodes])
            batch = columnar_batch()
            cluster.add_rows_columnar(batch)
            single.add_rows_columnar(columnar_batch())
            filters = filters_from_dict({"__name__": "ccm"})
            a = cluster.search_columns(filters, T0 - 10**6, T0 + 10**9)
            b = single.search_columns(filters, T0 - 10**6, T0 + 10**9)
            assert a.raw_names == b.raw_names
            np.testing.assert_array_equal(a.counts, b.counts)
            for s in range(a.n_series):
                n = int(a.counts[s])
                np.testing.assert_array_equal(a.ts[s, :n], b.ts[s, :n])
                np.testing.assert_array_equal(a.vals[s, :n], b.vals[s, :n])
            # per-series view agrees too (search_series wrapper)
            sa = cluster.search_series(filters, T0 - 10**6, T0 + 10**9)
            sb = single.search_series(filters, T0 - 10**6, T0 + 10**9)
            assert [s.metric_name.marshal() for s in sa] == \
                [s.metric_name.marshal() for s in sb]
            cluster.close()
        finally:
            for n in nodes:
                n.stop()
            single.close()

    def test_query_engine_over_columnar_cluster(self, tmp_path):
        """sum by over the cluster takes the columnar fetch path and
        matches the single-node result."""
        from victoriametrics_tpu.query.exec import exec_query
        from victoriametrics_tpu.query.types import EvalConfig
        nodes = make_nodes(tmp_path, n=3)
        single = Storage(str(tmp_path / "single"))
        try:
            cluster = ClusterStorage([n.client() for n in nodes])
            cluster.add_rows_columnar(columnar_batch())
            single.add_rows_columnar(columnar_batch())
            q = "sum by (job)(rate(ccm[1m]))"
            kw = dict(start=T0 + 60_000, end=T0 + 150_000, step=30_000,
                      tpu=None)
            ra = exec_query(EvalConfig(storage=cluster, **kw), q)
            rb = exec_query(EvalConfig(storage=single, **kw), q)
            assert len(ra) == len(rb) == 4
            da = {ts.metric_name.marshal(): ts.values for ts in ra}
            db = {ts.metric_name.marshal(): ts.values for ts in rb}
            assert set(da) == set(db)
            for k in da:
                np.testing.assert_allclose(da[k], db[k], rtol=1e-12)
            cluster.close()
        finally:
            for n in nodes:
                n.stop()
            single.close()
