"""Materialized streams + subscription push (query/matstream): the
cross-query amortization plane's tier-1 guards.

- ORACLE: frames reassembled by StreamClient are bit-equal to the
  polled ``query_range`` serialization of the same window (and to the
  cold ``disable_cache`` evaluation), per refresh, with live ingest.
- FLEET GUARD: storage reads per interval are O(distinct expressions) —
  ``samples_scanned`` per advance stays FLAT as subscribers go 1 -> 10
  (the fleet analog of test_refresh_suffix_guard), and on a real 2-node
  cluster the vmselect launches ONE search fan-out per interval
  regardless of subscriber count.
- BACKPRESSURE: a slow subscriber's queue is bounded; overflow drops
  the backlog and resyncs from one snapshot — never unbounded memory,
  and the resynced client still matches the poll.
- DECLINES: partial intervals and evaluation errors are never
  committed; subscribers see them loudly and the next clean advance
  resyncs.
- vmalert: rule groups evaluated through EngineDatasource produce
  byte-identical rows to the legacy HTTP poll path, with shared
  expressions evaluated once.
"""

import json
import math
import threading
import time
import urllib.parse
import urllib.request

import numpy as np
import pytest

from victoriametrics_tpu.httpapi.prometheus_api import PrometheusAPI
from victoriametrics_tpu.httpapi.server import HTTPServer
from victoriametrics_tpu.query import matstream
from victoriametrics_tpu.query import rollup_result_cache as rrc
from victoriametrics_tpu.query.exec import exec_query
from victoriametrics_tpu.query.format_value import fmt_value
from victoriametrics_tpu.query.matstream import StreamClient
from victoriametrics_tpu.query.types import EvalConfig
from victoriametrics_tpu.storage.storage import Storage

STEP = 60_000
SCRAPE = 15_000
NS = 8
NN = 240
Q = "sum by (g)(rate(ms_m[2m]))"
DUR = 20 * STEP


def _seed(s: Storage, t0: int, n: int = NN):
    rng = np.random.default_rng(7)
    rows = []
    for i in range(NS):
        vals = np.cumsum(rng.integers(0, 30, n)).astype(np.float64)
        rows.extend((({"__name__": "ms_m", "i": str(i), "g": f"g{i % 2}"},
                      t0 + j * SCRAPE, float(vals[j])) for j in range(n)))
    s.add_rows(rows)
    s.force_flush()


def _fresh(s: Storage, end: int, r: int):
    s.add_rows([({"__name__": "ms_m", "i": str(i), "g": f"g{i % 2}"},
                 end - STEP + (k + 1) * SCRAPE, float(9_000 + r * 7 + k))
                for i in range(NS) for k in range(4)])


@pytest.fixture()
def store(tmp_path):
    rrc.GLOBAL.reset()
    s = Storage(str(tmp_path / "s"))
    now = int(time.time() * 1000)
    t0 = (now - (NN - 1) * SCRAPE) // STEP * STEP
    _seed(s, t0)
    end0 = t0 + ((NN - 1) * SCRAPE // STEP + 1) * STEP
    yield s, end0
    s.close()


def polled(storage, q: str, start: int, end: int, step: int) -> list:
    """The oracle: a cold (nocache) evaluation serialized exactly the
    way h_query_range serializes a polled response."""
    ec = EvalConfig(start=start, end=end, step=step, storage=storage,
                    disable_cache=True)
    rows = exec_query(ec, q)
    grid = ec.timestamps() / 1e3
    out = []
    for r in rows:
        vals = [[float(t), fmt_value(v)]
                for t, v in zip(grid, r.values) if not math.isnan(v)]
        if vals:
            out.append({"metric": r.metric_name.to_dict(), "values": vals})
    out.sort(key=lambda e: json.dumps(e["metric"], sort_keys=True))
    return out


class FakeReq:
    def __init__(self, **kw):
        self._kw = {k: str(v) for k, v in kw.items()}

    def arg(self, name, default=""):
        return self._kw.get(name, default)

    def args(self, name):
        v = self._kw.get(name)
        return [v] if v is not None else []


class TestPushPollOracle:
    def test_frames_reassemble_bit_equal_to_poll(self, store):
        s, end = store
        api = PrometheusAPI(s)
        sub = api.matstreams.subscribe(Q, STEP, DUR)
        cli = StreamClient()
        f = sub.next_frame(timeout_s=1.0, now_ms=end)
        assert f["type"] == "snapshot"
        cli.apply(f)
        assert cli.result() == polled(s, Q, end - DUR, end, STEP)
        for r in range(4):
            end += STEP
            _fresh(s, end, r)
            f = sub.next_frame(timeout_s=1.0, now_ms=end)
            assert f["type"] == "delta", f
            cli.apply(f)
            assert cli.result() == polled(s, Q, end - DUR, end, STEP), (
                f"refresh {r}: pushed state diverged from poll")
        sub.close()
        assert api.matstreams.subscriber_count() == 0

    def test_delta_frames_are_suffix_sized(self, store):
        """A steady rolling refresh's delta must carry O(new columns),
        not the window — the push analog of the suffix guard."""
        s, end = store
        api = PrometheusAPI(s)
        sub = api.matstreams.subscribe(Q, STEP, DUR)
        sub.next_frame(timeout_s=1.0, now_ms=end)
        for r in range(3):
            end += STEP
            _fresh(s, end, r)
            f = sub.next_frame(timeout_s=1.0, now_ms=end)
            assert f["type"] == "delta"
            suffix_cols = (f["endMs"] - f["newStartMs"]) // STEP + 1
            n_cols = (f["endMs"] - f["startMs"]) // STEP + 1
            assert suffix_cols <= max(2, n_cols // 4), (
                f"delta carries {suffix_cols}/{n_cols} columns: "
                "suffix push has regressed to full-window frames")
        sub.close()

    def test_canonical_text_unifies_spellings(self, store):
        s, end = store
        api = PrometheusAPI(s)
        s1 = api.matstreams.subscribe(Q, STEP, DUR)
        s2 = api.matstreams.subscribe(
            "sum  by  (g) (rate( ms_m[2m] ))", STEP, DUR)
        assert api.matstreams.stream_count() == 1
        assert s1.stream is s2.stream
        s1.close()
        s2.close()


class TestFleetGuard:
    def test_samples_scanned_flat_1_to_10_subscribers(self, store):
        """THE fleet guard: storage reads per interval must not grow
        with subscriber count."""
        s, end = store
        api = PrometheusAPI(s)
        subs = [api.matstreams.subscribe(Q, STEP, DUR)]
        subs[0].next_frame(timeout_s=1.0, now_ms=end)
        stream = subs[0].stream
        end += STEP
        _fresh(s, end, 0)
        subs[0].next_frame(timeout_s=1.0, now_ms=end)
        samples_1sub = stream.last_samples_scanned
        evals_1sub = stream.evals
        assert samples_1sub > 0
        # fan out to 10 subscribers; each replays the window cold (no
        # eval), then the next interval costs ONE evaluation
        subs += [api.matstreams.subscribe(Q, STEP, DUR) for _ in range(9)]
        for sb in subs[1:]:
            f = sb.next_frame(timeout_s=1.0, now_ms=end)
            assert f["type"] == "snapshot"
        assert stream.evals == evals_1sub, "cold subscribes re-evaluated"
        end += STEP
        _fresh(s, end, 1)
        frames = []
        for sb in subs:  # first pump advances; the rest drain the fan-out
            frames.append(sb.next_frame(timeout_s=1.0, now_ms=end))
        assert all(f is not None for f in frames)
        assert stream.evals == evals_1sub + 1, (
            "one interval with 10 subscribers must cost exactly one eval")
        assert stream.last_samples_scanned == pytest.approx(
            samples_1sub, rel=0.5), (
            f"samples per interval grew with subscribers: "
            f"{samples_1sub} -> {stream.last_samples_scanned}")
        # every subscriber got the SAME delta
        assert len({json.dumps(f, sort_keys=True) for f in frames}) == 1
        for sb in subs:
            sb.close()

    def test_usage_rows_attribute_shared_fetch_once(self, store):
        s, end = store
        api = PrometheusAPI(s)
        subs = [api.matstreams.subscribe(Q, STEP, DUR) for _ in range(5)]
        for sb in subs:
            sb.next_frame(timeout_s=1.0, now_ms=end)
        resp = api.h_usage(FakeReq())
        data = json.loads(resp.body)["data"]
        rows = data["matstreams"]
        assert len(rows) == 1
        row = rows[0]
        assert row["subscribers"] == 5
        assert row["evals"] == 1, "shared fetch attributed per subscriber"
        assert row["samplesScanned"] == subs[0].stream.last_samples_scanned
        for sb in subs:
            sb.close()


class TestBackpressure:
    def test_slow_subscriber_bounded_drop_and_resync(self, store,
                                                     monkeypatch):
        monkeypatch.setenv("VM_MATSTREAM_QUEUE", "2")
        s, end = store
        api = PrometheusAPI(s)
        sub = api.matstreams.subscribe(Q, STEP, DUR)
        stream = sub.stream
        # never read: advance many intervals straight into the queue
        for r in range(7):
            end += STEP
            _fresh(s, end, r)
            assert stream.maybe_advance(end)
        assert sub.q.qsize() <= 2, "subscriber queue grew past the bound"
        assert sub.dropped > 0
        # drain: the resync snapshot catches the client up; state then
        # matches the poll exactly
        cli = StreamClient()
        saw_resync = False
        while True:
            f = sub.next_frame(timeout_s=0.0, now_ms=end)
            if f is None:
                break
            saw_resync = saw_resync or bool(f.get("resync"))
            cli.apply(f)
        assert saw_resync, "overflow must deliver a resync snapshot"
        assert cli.result() == polled(s, Q, end - DUR, end, STEP)
        sub.close()


class _PartialOnce:
    """Storage proxy: reports one partial interval, then clean."""

    def __init__(self, inner):
        self._inner = inner
        self._arm = False
        self._partial = False

    def arm(self):
        self._arm = True

    def reset_partial(self):
        self._partial = self._arm
        self._arm = False
        self._inner.reset_partial()

    @property
    def last_partial(self):
        return self._partial or self._inner.last_partial

    def __getattr__(self, name):
        return getattr(self._inner, name)


class TestDeclines:
    def test_partial_interval_declines_loudly_then_resyncs(self, store):
        s, end = store
        proxy = _PartialOnce(s)
        api = PrometheusAPI(proxy)
        sub = api.matstreams.subscribe(Q, STEP, DUR)
        cli = StreamClient()
        cli.apply(sub.next_frame(timeout_s=1.0, now_ms=end))
        committed_end = sub.stream._state.end
        declines0 = sub.stream.declines
        end += STEP
        _fresh(s, end, 0)
        proxy.arm()
        f = sub.next_frame(timeout_s=1.0, now_ms=end)
        assert f["type"] == "snapshot" and f.get("partial") is True
        assert sub.stream.declines == declines0 + 1
        assert sub.stream._state.end == committed_end, (
            "a partial interval must never commit")
        cli.apply(f)  # loudly-served partial view
        assert cli.partial
        # next clean interval resyncs from a snapshot and matches poll
        end += STEP
        _fresh(s, end, 1)
        f = sub.next_frame(timeout_s=1.0, now_ms=end)
        assert f["type"] == "snapshot", "post-decline frame must resync"
        cli.apply(f)
        assert not cli.partial
        assert cli.result() == polled(s, Q, end - DUR, end, STEP)
        sub.close()

    def test_eval_error_reaches_subscriber_then_recovers(self, store,
                                                         monkeypatch):
        s, end = store
        api = PrometheusAPI(s)
        sub = api.matstreams.subscribe(Q, STEP, DUR)
        sub.next_frame(timeout_s=1.0, now_ms=end)
        real = api._exec_range_cached

        def boom(ec, q, now_ms):
            raise RuntimeError("storage exploded")
        monkeypatch.setattr(api, "_exec_range_cached", boom)
        end += STEP
        f = sub.next_frame(timeout_s=1.0, now_ms=end)
        assert f["type"] == "error" and "exploded" in f["error"]
        monkeypatch.setattr(api, "_exec_range_cached", real)
        end += STEP
        _fresh(s, end, 0)
        f = sub.next_frame(timeout_s=1.0, now_ms=end)
        assert f["type"] == "snapshot"
        cli = StreamClient()
        cli.apply(f)
        assert cli.result() == polled(s, Q, end - DUR, end, STEP)
        sub.close()


class TestDisabled:
    def test_subscribe_raises_and_watch_503(self, store, monkeypatch):
        monkeypatch.setenv("VM_MATSTREAM", "0")
        s, end = store
        api = PrometheusAPI(s)
        with pytest.raises(matstream.MatStreamDisabled):
            api.matstreams.subscribe(Q, STEP, DUR)
        resp = api.h_watch(FakeReq(query=Q, step="1m", range="20m"))
        assert resp.status == 503

    def test_watch_bad_query_422(self, store):
        s, _ = store
        api = PrometheusAPI(s)
        resp = api.h_watch(FakeReq(query="sum by ((", step="1m"))
        assert resp.status == 422
        resp = api.h_watch(FakeReq())
        assert resp.status == 422


class TestHTTPWatch:
    def test_sse_stream_bit_equals_polled_query_range(self, store):
        """End to end over real HTTP: SSE frames -> StreamClient ==
        /api/v1/query_range?nocache=1 on the same window."""
        s, _ = store
        # a 1s step so wall-clock intervals elapse during the test; 1s
        # scrapes so rate[10s] windows hold samples
        now = int(time.time() * 1000)
        t0s = (now - 120_000) // 1000 * 1000
        s.add_rows([({"__name__": "ms_sse", "i": str(i), "g": f"g{i % 2}"},
                     t0s + j * 1000, float(j + 10 * i))
                    for i in range(4) for j in range(121)])
        s.force_flush()
        api = PrometheusAPI(s)
        srv = HTTPServer("127.0.0.1", 0)
        api.register(srv)
        srv.start()
        try:
            q = "sum by (g)(rate(ms_sse[10s]))"
            url = (f"http://127.0.0.1:{srv.port}/api/v1/watch?"
                   + urllib.parse.urlencode(
                       {"query": q, "step": "1s", "range": "30s",
                        "max_frames": "3", "heartbeat": "0.5"}))
            cli = StreamClient()
            frames = []
            with urllib.request.urlopen(url, timeout=30) as r:
                assert r.headers["Content-Type"] == "text/event-stream"
                for raw in r:
                    line = raw.decode().strip()
                    if line.startswith("data: "):
                        f = json.loads(line[len("data: "):])
                        frames.append(f)
                        cli.apply(f)
            assert len(frames) == 3
            assert frames[0]["type"] == "snapshot"
            start_ms, end_ms, _ = cli.window
            poll_url = (f"http://127.0.0.1:{srv.port}/api/v1/query_range?"
                        + urllib.parse.urlencode(
                            {"query": q, "start": start_ms / 1e3,
                             "end": end_ms / 1e3, "step": "1s",
                             "nocache": "1"}))
            with urllib.request.urlopen(poll_url, timeout=30) as r:
                assert r.status == 200
                body = r.read()
            want = json.loads(body)["data"]["result"]
            want.sort(key=lambda e: json.dumps(e["metric"],
                                               sort_keys=True))
            got = cli.result()
            # the polled values went through json float formatting;
            # compare through one json round trip on both sides
            assert json.loads(json.dumps(got)) == \
                json.loads(json.dumps(want))
        finally:
            srv.stop()

    def test_disconnect_before_first_chunk_does_not_leak(self, store):
        """A client that drops before the SSE generator starts must not
        leak its subscription: a never-started generator's finally
        blocks don't run on close(), so StreamingResponse.on_close is
        the cleanup path the server invokes either way."""
        s, _ = store
        api = PrometheusAPI(s)
        resp = api.h_watch(FakeReq(query=Q, step="1m", range="20m"))
        assert api.matstreams.subscriber_count() == 1
        assert resp.on_close is not None
        # the server's _send_stream finally: close the (never-started)
        # generator, then on_close
        resp.chunks.close()
        resp.on_close()
        assert api.matstreams.subscriber_count() == 0
        resp.on_close()  # idempotent

    def test_heartbeat_zero_does_not_busy_spin(self, store):
        """heartbeat=0 must clamp to a real wait, not a hot keepalive
        loop (one-request CPU DoS)."""
        s, _ = store
        api = PrometheusAPI(s)
        resp = api.h_watch(FakeReq(query=Q, step="1m", range="20m",
                                   heartbeat="0"))
        chunks = resp.chunks
        try:
            first = next(chunks)            # the cold snapshot frame
            assert b"event: frame" in first
            t0 = time.monotonic()
            got = 0
            while got < 2:                  # then idle keepalives
                c = next(chunks)
                if c.startswith(b"event: frame"):
                    # a real 1m interval boundary can cross mid-test and
                    # legitimately emit a frame; only keepalives count
                    continue
                assert c == b": keepalive\n\n"
                got += 1
            assert time.monotonic() - t0 >= 0.3, (
                "keepalives arrived back-to-back: heartbeat=0 spins")
        finally:
            chunks.close()

    def test_eviction_never_takes_a_subscribed_stream(self, store,
                                                      monkeypatch):
        monkeypatch.setenv("VM_MATSTREAM_MAX", "1")
        s, _ = store
        api = PrometheusAPI(s)
        q2 = "max by (g)(rate(ms_m[2m]))"
        sub1 = api.matstreams.subscribe(Q, STEP, DUR)
        with pytest.raises(matstream.MatStreamLimitError):
            api.matstreams.subscribe(q2, STEP, DUR)
        sub1.close()
        sub2 = api.matstreams.subscribe(q2, STEP, DUR)  # evicts idle Q
        assert api.matstreams.stream_count() == 1
        assert sub2.stream.q == api.matstreams.canonical(q2)
        sub2.close()

    def test_frame_encoding_shared_across_subscribers(self):
        f = {"type": "delta", "seq": 3, "result": []}
        a = matstream.encode_frame(f)
        b = matstream.encode_frame(f)
        assert a is b, "shared frame re-serialized per subscriber"
        assert json.loads(a) == f

    def test_watch_registered_on_select_mode(self, store):
        s, _ = store
        api = PrometheusAPI(s)
        srv = HTTPServer("127.0.0.1", 0)
        api.register(srv, mode="select")
        assert srv._route_for("/api/v1/watch") is not None


class TestVmalertEngine:
    def _group_cfg(self):
        return {
            "name": "g1", "interval": "30s",
            "rules": [
                {"record": "g:rate:sum",
                 "expr": "sum by (g)(rate(ms_m[2m]))"},
                {"record": "g:rate:sum2",
                 "expr": "sum by (g)(rate(ms_m[2m]))"},
                {"record": "g:rate:max",
                 "expr": "max by (g)(rate(ms_m[2m]))"},
                {"alert": "HighRate",
                 "expr": "sum by (g)(rate(ms_m[2m])) > 0",
                 "labels": {"sev": "page"}},
            ]}

    def test_engine_rows_identical_to_legacy_poll(self, store):
        from victoriametrics_tpu.apps import vmalert as va
        s, end = store
        api = PrometheusAPI(s)
        srv = HTTPServer("127.0.0.1", 0)
        api.register(srv)
        srv.start()
        try:
            legacy_rows, engine_rows = [], []

            class Cap:
                def __init__(self, sink):
                    self.sink = sink

                def write(self, rows):
                    self.sink.extend(rows)

            ds_legacy = va.Datasource(f"http://127.0.0.1:{srv.port}")
            ds_engine = va.EngineDatasource(api)
            g_legacy = va.Group(self._group_cfg(), ds_legacy, [],
                                Cap(legacy_rows))
            g_engine = va.Group(self._group_cfg(), ds_engine, [],
                                Cap(engine_rows))
            ts = end / 1e3
            reuse0 = api.matstreams.instant_reuse
            evals0 = api.matstreams.instant_evals
            g_legacy.eval_once(ts, notify=False)
            g_engine.eval_once(ts, notify=False)

            def norm(rows):
                return sorted(
                    (tuple(sorted(labels.items())), ts_ms, v)
                    for labels, ts_ms, v in rows)
            assert norm(engine_rows) == norm(legacy_rows), (
                "engine-evaluated rules diverged from the HTTP poll path")
            assert legacy_rows, "harness produced no rows"
            # 4 rules -> 3 distinct expressions (the two identical
            # recording rules share one evaluation)
            assert api.matstreams.instant_evals - evals0 == 3
            assert api.matstreams.instant_reuse - reuse0 == 1
        finally:
            srv.stop()

    def test_engine_disabled_degrades_to_per_rule_eval(self, store,
                                                       monkeypatch):
        from victoriametrics_tpu.apps import vmalert as va
        monkeypatch.setenv("VM_MATSTREAM", "0")
        s, end = store
        api = PrometheusAPI(s)
        ds = va.EngineDatasource(api)
        evals0 = api.matstreams.instant_evals
        r1 = ds.query("sum by (g)(rate(ms_m[2m]))", end / 1e3)
        r2 = ds.query("sum by (g)(rate(ms_m[2m]))", end / 1e3)
        assert r1 == r2 and r1
        # no memo: both calls evaluated (the legacy oracle semantics)
        assert api.matstreams.instant_evals - evals0 == 2

    def test_vmsingle_hosts_server_side_rules(self, tmp_path):
        """vmsingle -rule: recording rules evaluate in-process through
        the engine and land in the local storage."""
        from victoriametrics_tpu.apps.vmsingle import build, parse_flags
        rule = tmp_path / "rules.yml"
        rule.write_text(json.dumps({
            "groups": [{"name": "g", "interval": "30s", "rules": [
                {"record": "r:ms_sum", "expr": "sum(ms_rule_m)"}]}]}))
        args = parse_flags([f"-storageDataPath={tmp_path}/data",
                            "-httpListenAddr=127.0.0.1:0",
                            f"-rule={rule}"])
        storage, srv, api = build(args)
        try:
            assert len(api.rule_groups) == 1
            now = int(time.time() * 1000)
            storage.add_rows([({"__name__": "ms_rule_m", "i": str(i)},
                               now - 30_000, float(i)) for i in range(4)])
            storage.force_flush()
            api.rule_groups[0].eval_once(now / 1e3, notify=False)
            storage.force_flush()
            from victoriametrics_tpu.storage.tag_filters import TagFilter
            rows = list(storage.search_series(
                [TagFilter(b"", b"r:ms_sum")],
                now - 600_000, now + 600_000))
            assert rows, "recording rule result did not land in storage"
            assert rows[0].metric_name.metric_group == b"r:ms_sum"
            assert srv._route_for("/api/v1/rules") is not None
        finally:
            for g in api.rule_groups:
                g.stop()
            srv.stop()
            storage.close()


class TestClusterFanOnce:
    def test_vmselect_fans_storage_once_per_interval(self, tmp_path):
        """On a real 2-node cluster, N subscribers of one expression
        cost ONE search fan-out per interval — the vmselect half of the
        O(distinct expressions) contract."""
        from victoriametrics_tpu.parallel.cluster_api import (
            ClusterStorage, StorageNodeClient, make_storage_handlers)
        from victoriametrics_tpu.parallel.rpc import (HELLO_INSERT,
                                                      HELLO_SELECT,
                                                      RPCServer)
        stores, servers, nodes = [], [], []
        for k in range(2):
            st = Storage(str(tmp_path / f"n{k}"))
            stores.append(st)
            h = make_storage_handlers(st)
            isrv = RPCServer("127.0.0.1", 0, HELLO_INSERT, h)
            ssrv = RPCServer("127.0.0.1", 0, HELLO_SELECT, h)
            isrv.start()
            ssrv.start()
            servers += [isrv, ssrv]
            nodes.append(StorageNodeClient("127.0.0.1", isrv.port,
                                           ssrv.port, name=f"n{k}"))
        cluster = ClusterStorage(nodes)
        try:
            now = int(time.time() * 1000)
            t0 = (now - (NN - 1) * SCRAPE) // STEP * STEP
            rng = np.random.default_rng(5)
            rows = []
            for i in range(NS):
                vals = np.cumsum(rng.integers(0, 30, NN)).astype(float)
                rows.extend((({"__name__": "ms_m", "i": str(i),
                               "g": f"g{i % 2}"}, t0 + j * SCRAPE,
                              float(vals[j])) for j in range(NN)))
            cluster.add_rows(rows)
            for st in stores:
                st.force_flush()
            end = t0 + ((NN - 1) * SCRAPE // STEP + 1) * STEP
            api = PrometheusAPI(cluster)
            subs = [api.matstreams.subscribe(Q, STEP, DUR)
                    for _ in range(3)]
            fan0 = cluster.search_fanouts
            first = [sb.next_frame(timeout_s=2.0, now_ms=end)
                     for sb in subs]
            assert all(f and f["type"] == "snapshot" for f in first)
            cold_fans = cluster.search_fanouts - fan0
            for r in range(2):
                end += STEP
                fan_r = cluster.search_fanouts
                frames = [sb.next_frame(timeout_s=2.0, now_ms=end)
                          for sb in subs]
                assert all(f is not None for f in frames)
                assert len({json.dumps(f, sort_keys=True)
                            for f in frames}) == 1
                delta_fans = cluster.search_fanouts - fan_r
                assert delta_fans <= cold_fans, (
                    f"interval {r}: {delta_fans} fan-outs for 3 "
                    f"subscribers (cold eval cost {cold_fans}) — the "
                    "shared evaluator is gone")
            # the whole 3-subscriber run costs what ONE subscriber's
            # refreshes cost; a per-subscriber poll loop would have
            # tripled it
            for sb in subs:
                sb.close()
        finally:
            for srv in servers:
                srv.stop()
            cluster.close()
            for st in stores:
                st.close()


@pytest.mark.race
class TestMatStreamRace:
    def test_concurrent_subscribe_ingest_advance_unsubscribe(self, store):
        """Race stress (tools/race.sh): subscriber churn + live ingest +
        concurrent pumps over one stream; the steady subscriber's final
        reassembled state must equal the poll, queues stay bounded, no
        exceptions escape."""
        s, end0 = store
        api = PrometheusAPI(s)
        steady = api.matstreams.subscribe(Q, STEP, DUR)
        cli = StreamClient()
        cli.apply(steady.next_frame(timeout_s=2.0, now_ms=end0))
        stop = threading.Event()
        errors: list = []
        now_box = [end0]

        def ingester():
            # IDEMPOTENT values (a pure function of the timestamp):
            # rewrites racing an advance then stay invisible to the
            # final poll-vs-push comparison
            while not stop.is_set():
                end = now_box[0] + STEP
                s.add_rows([
                    ({"__name__": "ms_m", "i": str(i), "g": f"g{i % 2}"},
                     end - STEP + (k + 1) * SCRAPE,
                     float((end // SCRAPE + k) % 1000))
                    for i in range(NS) for k in range(4)])
                time.sleep(0.002)

        def churner():
            try:
                while not stop.is_set():
                    sub = api.matstreams.subscribe(Q, STEP, DUR)
                    sub.next_frame(timeout_s=0.05, now_ms=now_box[0])
                    sub.close()
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def pumper():
            try:
                while not stop.is_set():
                    api.matstreams.advance_due(now_box[0])
                    time.sleep(0.001)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=f, daemon=True)
                   for f in (ingester, churner, pumper, pumper)]
        for t in threads:
            t.start()
        end = end0
        try:
            for _ in range(6):
                end += STEP
                now_box[0] = end
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    f = steady.next_frame(timeout_s=0.2, now_ms=end)
                    if f is not None:
                        cli.apply(f)
                    if cli.window and cli.window[1] >= end:
                        break
                assert cli.window and cli.window[1] >= end, (
                    "stream stopped advancing under concurrency")
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)
        assert not errors, errors
        assert steady.q.qsize() <= matstream.queue_limit()
        # quiesced: one final advance over a fresh interval sees the
        # final data, then the oracle must hold exactly
        end += STEP
        api.matstreams.advance_due(end)
        while True:
            f = steady.next_frame(timeout_s=0.0, now_ms=end)
            if f is None:
                break
            cli.apply(f)
        assert cli.window[1] == end
        assert cli.result() == polled(s, Q, cli.window[0], cli.window[1],
                                      STEP)
        steady.close()


class TestResumeToken:
    """/api/v1/watch reconnect/resume (ISSUE 15 satellite): a dropped
    subscriber re-attaches with its last ``<epoch>:<seq>`` token and
    receives only the missed suffix frames; too-old or foreign tokens
    degrade LOUDLY to one resync snapshot."""

    def _drain(self, sub, cli, now):
        frames = []
        while True:
            f = sub.next_frame(timeout_s=0.0, now_ms=now)
            if f is None:
                return frames
            frames.append(f)
            cli.apply(f)

    def test_resume_replays_only_missed_suffix(self, store):
        s, end = store
        api = PrometheusAPI(s)
        reg = api.matstreams
        sub = reg.subscribe(Q, STEP, DUR)
        cli = StreamClient()
        frames = self._drain(sub, cli, end)
        assert frames and frames[0]["type"] == "snapshot"
        stream = sub.stream
        token = stream.resume_token(frames[-1])
        sub.close()                      # the dashboard drops
        # the stream advances twice while the client is gone
        for r in range(2):
            end += STEP
            _fresh(s, end, r)
            reg.advance_due(end)
        from victoriametrics_tpu.query.matstream import (_RESUME_MISSES,
                                                         _RESUMES)
        r0, m0 = _RESUMES.get(), _RESUME_MISSES.get()
        sub2 = reg.subscribe(Q, STEP, DUR, resume=token)
        missed = self._drain(sub2, cli, end)
        assert _RESUMES.get() == r0 + 1
        assert _RESUME_MISSES.get() == m0
        # ONLY the two missed deltas — no snapshot replay
        assert [f["type"] for f in missed] == ["delta", "delta"]
        # and the reassembled state matches the cold poll bit for bit
        assert json.loads(json.dumps(cli.result())) == \
            json.loads(json.dumps(polled(s, Q, end - DUR, end, STEP)))
        sub2.close()

    def test_resume_current_seq_sends_nothing(self, store):
        s, end = store
        api = PrometheusAPI(s)
        sub = api.matstreams.subscribe(Q, STEP, DUR)
        cli = StreamClient()
        frames = self._drain(sub, cli, end)
        token = sub.stream.resume_token(frames[-1])
        sub.close()
        sub2 = api.matstreams.subscribe(Q, STEP, DUR, resume=token)
        assert self._drain(sub2, cli, end) == []   # nothing missed
        # the next advance delivers a plain delta (client state valid)
        end += STEP
        _fresh(s, end, 9)
        api.matstreams.advance_due(end)
        nxt = self._drain(sub2, cli, end)
        assert [f["type"] for f in nxt] == ["delta"]
        sub2.close()

    def test_too_old_token_degrades_to_resync_snapshot(self, store):
        s, end = store
        api = PrometheusAPI(s)
        reg = api.matstreams
        sub = reg.subscribe(Q, STEP, DUR)
        cli = StreamClient()
        frames = self._drain(sub, cli, end)
        token = sub.stream.resume_token(frames[-1])
        sub.close()
        # advance PAST the retained ring (VM_MATSTREAM_QUEUE frames)
        for r in range(matstream.queue_limit() + 2):
            end += STEP
            _fresh(s, end, r)
            reg.advance_due(end)
        from victoriametrics_tpu.query.matstream import _RESUME_MISSES
        m0 = _RESUME_MISSES.get()
        sub2 = reg.subscribe(Q, STEP, DUR, resume=token)
        got = self._drain(sub2, cli, end)
        assert _RESUME_MISSES.get() == m0 + 1
        assert got[0]["type"] == "snapshot" and got[0].get("resync")
        assert json.loads(json.dumps(cli.result())) == \
            json.loads(json.dumps(polled(s, Q, end - DUR, end, STEP)))
        sub2.close()

    def test_foreign_epoch_token_is_a_miss(self, store):
        s, end = store
        api = PrometheusAPI(s)
        api.matstreams.subscribe(Q, STEP, DUR).close()
        from victoriametrics_tpu.query.matstream import _RESUME_MISSES
        m0 = _RESUME_MISSES.get()
        sub = api.matstreams.subscribe(Q, STEP, DUR,
                                       resume="deadbeef.1:3")
        cli = StreamClient()
        got = self._drain(sub, cli, end)
        assert _RESUME_MISSES.get() == m0 + 1
        assert got and got[0]["type"] == "snapshot"
        sub.close()

    def test_sse_frames_carry_resume_id(self, store):
        """The HTTP surface: every SSE event ships an ``id:`` line the
        browser echoes back as Last-Event-ID, and h_watch accepts both
        that header and the resume= arg."""
        s, _ = store
        api = PrometheusAPI(s)
        resp = api.h_watch(FakeReq(query=Q, step="1m", range="20m",
                                   max_frames="1"))
        chunks = list(resp.chunks)
        assert any(b"\nid: " in c for c in chunks)
        # the id round-trips through the resume path (arg form)
        idline = next(c for c in chunks if b"\nid: " in c)
        token = idline.split(b"\nid: ")[1].split(b"\n")[0].decode()
        from victoriametrics_tpu.query.matstream import _RESUMES
        r0 = _RESUMES.get()
        resp2 = api.h_watch(FakeReq(query=Q, step="1m", range="20m",
                                    max_frames="1", resume=token,
                                    heartbeat="0.2"))
        resp2.on_close()
        assert _RESUMES.get() == r0 + 1


class TestInstantShareWithRangeStreams:
    """ISSUE 15 satellite: rule groups and RANGE streams over one
    expression share one evaluation per distinct (expr, ts) — the
    stream's committed tail column serves the instant after a one-time
    validate-then-trust equality check."""

    def test_stream_tail_serves_instant_after_validation(self, store):
        s, end = store
        api = PrometheusAPI(s)
        reg = api.matstreams
        sub = reg.subscribe(Q, STEP, DUR)
        cli = StreamClient()
        while True:
            f = sub.next_frame(timeout_s=0.0, now_ms=end)
            if f is None:
                break
            cli.apply(f)
        st = sub.stream
        assert st.instant_share is None
        # first instant at the committed end: validates (one legacy
        # eval, which was owed anyway) and records the verdict
        e0 = reg.instant_evals
        rows1 = reg.instant_vector(Q, end)
        assert reg.instant_evals == e0 + 1
        assert st.instant_share is True, \
            "window-explicit expression must validate as shareable"
        # advance the stream; the instant at the NEW end is served from
        # the committed tail column: zero evaluations
        end += STEP
        _fresh(s, end, 3)
        reg.advance_due(end)
        e1, reuse1 = reg.instant_evals, reg.instant_reuse
        rows2 = reg.instant_vector(Q, end)
        assert reg.instant_evals == e1, "shared instant re-evaluated"
        assert reg.instant_reuse == reuse1 + 1
        # ...and is bit-equal to what the legacy path would compute
        from victoriametrics_tpu.query.exec import exec_query as _xq
        ec = api._ec(end, end, 300_000, (0, 0))
        want = []
        for r in _xq(ec, reg.canonical(Q)):
            v = r.values[-1]
            if not math.isnan(v):
                want.append({"metric": r.metric_name.to_dict(),
                             "value": float(fmt_value(v)),
                             "ts": end / 1e3})
        key = lambda r: json.dumps(r, sort_keys=True)  # noqa: E731
        assert sorted(rows2, key=key) == sorted(want, key=key)
        assert rows1  # the validated call returned real rows too
        # every Nth share REVALIDATES against a fresh legacy eval
        # (bounding divergence from late-arriving samples): drive the
        # hit counter to the revalidation boundary and observe exactly
        # one extra eval that restores the True verdict
        n = reg._SHARE_REVALIDATE_N
        e2 = reg.instant_evals
        extra = 0
        for j in range(n):
            end += STEP
            _fresh(s, end, 10 + j)
            reg.advance_due(end)
            before = reg.instant_evals
            reg.instant_vector(Q, end)
            extra += reg.instant_evals - before
        assert extra == 1, f"expected exactly one revalidation, {extra}"
        assert st.instant_share is True
        assert reg.instant_evals == e2 + 1
        sub.close()

    def test_unaligned_ts_does_not_share(self, store):
        s, end = store
        api = PrometheusAPI(s)
        reg = api.matstreams
        sub = reg.subscribe(Q, STEP, DUR)
        while sub.next_frame(timeout_s=0.0, now_ms=end) is not None:
            pass
        e0 = reg.instant_evals
        reg.instant_vector(Q, end + 7_000)   # off the committed end
        assert reg.instant_evals == e0 + 1
        assert sub.stream.instant_share is None  # never consulted
        sub.close()

    def test_divergent_expression_pins_share_off(self, store):
        """An expression whose instant value differs from the range
        tail must validate to False ONCE and never share after."""
        s, end = store
        api = PrometheusAPI(s)
        reg = api.matstreams
        sub = reg.subscribe(Q, STEP, DUR)
        while sub.next_frame(timeout_s=0.0, now_ms=end) is not None:
            pass
        st = sub.stream
        # sabotage the committed tail so validation MUST fail
        with st._lock:
            st._state.vals[:, -1] += 1.0
        e0 = reg.instant_evals
        reg.instant_vector(Q, end)
        assert reg.instant_evals == e0 + 1
        assert st.instant_share is False
        # subsequent instants keep evaluating (no silent wrong shares)
        reg.instant_vector(Q, end + STEP)
        assert reg.instant_evals == e0 + 2
        sub.close()

    def test_resume_across_decline_degrades_to_snapshot(self, store):
        """A missed suffix that crosses a decline (error frame) must
        NOT replay — the retained delta after it was diffed against
        the committed state, not the state a declined client holds —
        it degrades to the loud snapshot+resync path instead."""
        s, end = store
        api = PrometheusAPI(s)
        reg = api.matstreams
        sub = reg.subscribe(Q, STEP, DUR)
        client = StreamClient()
        frames = []
        while True:
            f = sub.next_frame(timeout_s=0.0, now_ms=end)
            if f is None:
                break
            frames.append(f)
            client.apply(f)
        token = sub.stream.resume_token(frames[-1])
        sub.close()
        # one ERROR advance (evaluation raises), then a clean delta
        orig = api._exec_range_cached
        api._exec_range_cached = lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("injected decline"))
        end += STEP
        reg.advance_due(end)
        api._exec_range_cached = orig
        end += STEP
        _fresh(s, end, 5)
        reg.advance_due(end)
        from victoriametrics_tpu.query.matstream import _RESUME_MISSES
        m0 = _RESUME_MISSES.get()
        sub2 = reg.subscribe(Q, STEP, DUR, resume=token)
        got = []
        while True:
            f = sub2.next_frame(timeout_s=0.0, now_ms=end)
            if f is None:
                break
            got.append(f)
            client.apply(f)
        assert _RESUME_MISSES.get() == m0 + 1
        assert got and got[0]["type"] == "snapshot" and \
            got[0].get("resync")
        assert json.loads(json.dumps(client.result())) == \
            json.loads(json.dumps(polled(s, Q, end - DUR, end, STEP)))
        sub2.close()

    def test_resume_token_at_partial_frame_is_a_miss(self, store):
        """A token naming a PARTIAL snapshot frame must not resume:
        the client's window holds the uncommitted partial values, so
        replayed deltas (diffed against the committed state) would
        leave a silently divergent prefix — resync instead."""
        s, end = store
        api = PrometheusAPI(s)
        reg = api.matstreams
        sub = reg.subscribe(Q, STEP, DUR)
        client = StreamClient()
        while True:
            f = sub.next_frame(timeout_s=0.0, now_ms=end)
            if f is None:
                break
            client.apply(f)
        st = sub.stream
        sub.close()
        # manufacture a fanned partial-decline frame in the retained
        # ring (the real path needs a mid-fan-out storage failure)
        with st._lock:
            st.seq += 1
            st._recent.append((st.seq, st._snapshot_frame(partial=True)))
        token = f"{st.epoch}:{st.seq}"
        from victoriametrics_tpu.query.matstream import _RESUME_MISSES
        m0 = _RESUME_MISSES.get()
        sub2 = reg.subscribe(Q, STEP, DUR, resume=token)
        got = []
        while True:
            f = sub2.next_frame(timeout_s=0.0, now_ms=end)
            if f is None:
                break
            got.append(f)
        assert _RESUME_MISSES.get() == m0 + 1
        assert got and got[0]["type"] == "snapshot" and \
            got[0].get("resync")
        sub2.close()
